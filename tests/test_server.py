"""Server integration tests over real sockets, porting the reference's
fixture pattern (`server_test.go:78-238`): port-0 listeners, 50ms flush
interval, channel sink delivering each flush to the test."""

import os
import queue
import socket
import ssl
import subprocess
import time
import urllib.request

import pytest

from veneur_tpu import config as config_mod
from veneur_tpu import http_api
from veneur_tpu.core.server import Server
from veneur_tpu.sinks import simple as simple_sinks


def make_config(**kw) -> config_mod.Config:
    cfg = config_mod.Config(
        statsd_listen_addresses=["udp://127.0.0.1:0"],
        interval=0.05,
        percentiles=[0.5],
        aggregates=["min", "max", "count"],
        hostname="testbox",
        num_readers=2,
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


@pytest.fixture
def fixture_server():
    servers = []

    def boot(**kw):
        cfg = make_config(**kw)
        sink = simple_sinks.ChannelMetricSink()
        srv = Server(cfg, extra_metric_sinks=[sink])
        srv.start()
        servers.append(srv)
        return srv, sink

    yield boot
    for srv in servers:
        srv.shutdown()


def drain_until(sink, pred, timeout=5.0):
    """Collect flushed metric batches until pred(all) or timeout."""
    all_metrics = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            batch = sink.queue.get(timeout=0.1)
        except queue.Empty:
            continue
        all_metrics.extend(batch)
        if pred(all_metrics):
            return all_metrics
    raise AssertionError(f"timed out; got {[m.name for m in all_metrics]}")


def test_udp_end_to_end(fixture_server):
    srv, sink = fixture_server()
    kind, addr = srv.statsd_addrs[0]
    assert kind == "udp"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(b"a.b.c:42|c|#x:y\ntemp:70|g", addr)
    s.close()
    srv.flush_count = 0
    # flush manually (no ticker thread in tests)
    time.sleep(0.1)
    srv.flush()
    ms = drain_until(sink, lambda all_m: len(all_m) >= 2)
    by = {m.name: m for m in ms}
    assert by["a.b.c"].value == 42.0
    assert by["a.b.c"].tags == ["x:y"]
    assert by["temp"].value == 70.0


def test_udp_multiple_readers_shared_port(fixture_server):
    srv, sink = fixture_server(num_readers=4)
    _, addr = srv.statsd_addrs[0]
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    for i in range(100):
        s.sendto(f"hits:1|c".encode(), addr)
    s.close()
    time.sleep(0.3)
    srv.flush()
    ms = drain_until(sink, lambda all_m: any(m.name == "hits" for m in all_m))
    hits = [m for m in ms if m.name == "hits"]
    assert sum(m.value for m in hits) == 100.0


def test_tcp_end_to_end(fixture_server):
    srv, sink = fixture_server(
        statsd_listen_addresses=["tcp://127.0.0.1:0"])
    _, addr = srv.statsd_addrs[0]
    c = socket.create_connection(addr)
    c.sendall(b"tcp.metric:7|c\n")
    c.close()
    time.sleep(0.2)
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "tcp.metric" for m in a))
    assert [m for m in ms if m.name == "tcp.metric"][0].value == 7.0


def test_unixgram_end_to_end(fixture_server, tmp_path):
    path = str(tmp_path / "statsd.sock")
    srv, sink = fixture_server(
        statsd_listen_addresses=[f"unixgram://{path}"])
    c = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    c.sendto(b"ux:3|c", path)
    c.close()
    time.sleep(0.2)
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "ux" for m in a))
    assert [m for m in ms if m.name == "ux"][0].value == 3.0


def test_unix_stream_end_to_end(fixture_server, tmp_path):
    path = str(tmp_path / "statsd-stream.sock")
    srv, sink = fixture_server(statsd_listen_addresses=[f"unix://{path}"])
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.connect(path)
    c.sendall(b"uxs:9|g\n")
    c.close()
    time.sleep(0.2)
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "uxs" for m in a))
    assert [m for m in ms if m.name == "uxs"][0].value == 9.0


def _make_certs(tmp_path):
    """Self-signed CA + server + client certs via openssl CLI."""
    ca_key = tmp_path / "ca.key"
    ca_crt = tmp_path / "ca.crt"
    def run(*args):
        subprocess.run(args, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=test-ca")
    ext = tmp_path / "san.cnf"
    ext.write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    certs = {}
    for who in ("server", "client"):
        key = tmp_path / f"{who}.key"
        csr = tmp_path / f"{who}.csr"
        crt = tmp_path / f"{who}.crt"
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr),
            "-subj", f"/CN=127.0.0.1")
        # SANs required: gRPC's TLS stack ignores CN-only certs
        run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
            "-CAkey", str(ca_key), "-CAcreateserial", "-days", "1",
            "-extfile", str(ext), "-out", str(crt))
        certs[who] = (str(key), str(crt))
    return str(ca_crt), certs


@pytest.mark.skipif(
    subprocess.run(["which", "openssl"], capture_output=True).returncode != 0,
    reason="openssl unavailable")
def test_tls_client_cert_required(fixture_server, tmp_path):
    ca, certs = _make_certs(tmp_path)
    skey, scrt = certs["server"]
    ckey, ccrt = certs["client"]
    srv, sink = fixture_server(
        statsd_listen_addresses=["tcp://127.0.0.1:0"],
        tls_key=skey, tls_certificate=scrt, tls_authority_certificate=ca)
    _, addr = srv.statsd_addrs[0]

    # correct client cert works
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    ctx.load_cert_chain(ccrt, ckey)
    raw = socket.create_connection(addr)
    tls = ctx.wrap_socket(raw)
    tls.sendall(b"tls.metric:5|c\n")
    tls.close()
    time.sleep(0.3)
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "tls.metric" for m in a))
    assert [m for m in ms if m.name == "tls.metric"][0].value == 5.0

    # no client cert is rejected
    ctx2 = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx2.check_hostname = False
    ctx2.verify_mode = ssl.CERT_NONE
    raw2 = socket.create_connection(addr)
    with pytest.raises(ssl.SSLError):
        tls2 = ctx2.wrap_socket(raw2)
        tls2.sendall(b"evil:1|c\n")
        tls2.recv(1)  # force handshake completion
    time.sleep(0.2)
    srv.flush()
    srv.egress.settle(timeout_s=5.0)   # fan-out is async now
    while not sink.queue.empty():
        batch = sink.queue.get()
        assert not any(m.name == "evil" for m in batch)


def test_events_reach_sink_other_samples(fixture_server):
    srv, sink = fixture_server()
    _, addr = srv.statsd_addrs[0]
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(b"_e{5,5}:hello|world|t:info", addr)
    s.close()
    time.sleep(0.2)
    srv.flush()
    deadline = time.time() + 2
    while time.time() < deadline and not sink.other_samples:
        time.sleep(0.05)
    assert sink.other_samples
    assert sink.other_samples[0].name == "hello"


def test_ticker_flushes(fixture_server):
    import threading
    srv, sink = fixture_server()
    _, addr = srv.statsd_addrs[0]
    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(b"tick:1|c", addr)
    s.close()
    ms = drain_until(sink, lambda a: any(m.name == "tick" for m in a))
    assert ms
    srv.shutdown()


def test_watchdog_fires():
    cfg = make_config(flush_watchdog_missed_flushes=2, interval=0.05)
    srv = Server(cfg)
    fired = []
    srv.shutdown_hook = lambda: fired.append(True)
    srv.last_flush_unix = time.time() - 10  # long overdue
    srv.start()
    deadline = time.time() + 2
    while time.time() < deadline and not fired:
        time.sleep(0.02)
    srv.shutdown()
    assert fired


def test_http_api(fixture_server):
    srv, _ = fixture_server(http_config_endpoint=True)
    api = http_api.HttpApi(srv, "127.0.0.1:0")
    api.start()
    host, port = api.address
    base = f"http://{host}:{port}"
    assert urllib.request.urlopen(base + "/healthcheck").read() == b"ok\n"
    assert urllib.request.urlopen(base + "/version").read()
    cfg_json = urllib.request.urlopen(base + "/config/json").read()
    assert b"interval" in cfg_json
    assert b"REDACTED" not in cfg_json  # no secrets set
    dbg = urllib.request.urlopen(base + "/debug/vars").read()
    assert b"flush_count" in dbg
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(base + "/nope")
    api.stop()


def test_config_yaml_roundtrip(tmp_path, monkeypatch):
    p = tmp_path / "veneur.yaml"
    p.write_text("""
interval: "5s"
percentiles: [0.5, 0.99]
aggregates: ["max", "count"]
statsd_listen_addresses:
  - udp://127.0.0.1:8126
forward_address: "$FORWARD_TARGET"
metric_sinks:
  - kind: blackhole
    name: bh
""")
    env = {"FORWARD_TARGET": "globalbox:3000",
           "VENEUR_HOSTNAME": "overridden"}
    cfg = config_mod.read_config(str(p), environ=env)
    assert cfg.interval == 5.0
    assert cfg.percentiles == [0.5, 0.99]
    assert cfg.forward_address == "globalbox:3000"
    assert cfg.is_local
    assert cfg.hostname == "overridden"
    assert cfg.metric_sinks[0].kind == "blackhole"


def test_config_strict_rejects_unknown(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("no_such_field: 1\n")
    with pytest.raises(ValueError):
        config_mod.read_config(str(p), strict=True, environ={})
    cfg = config_mod.read_config(str(p), strict=False, environ={})
    assert cfg.interval == 10.0


def test_ingest_knob_validation(tmp_path):
    """The ingest_* data-plane knobs strict-parse from YAML, clamp
    negative counts to 0 (engine default), and reject unknown dispatch
    enum values loudly."""
    p = tmp_path / "ingest.yaml"
    p.write_text("""
ingest_reader_shards: 4
ingest_reader_pinning: true
ingest_reader_batch: 128
ingest_simd: sse2
ingest_backend: recvmmsg
ingest_ring_slots: 2048
""")
    cfg = config_mod.read_config(str(p), strict=True, environ={})
    assert cfg.ingest_reader_shards == 4
    assert cfg.ingest_reader_pinning is True
    assert cfg.ingest_reader_batch == 128
    assert cfg.ingest_simd == "sse2"
    assert cfg.ingest_backend == "recvmmsg"
    assert cfg.ingest_ring_slots == 2048

    neg = config_mod.Config(ingest_reader_shards=-3, ingest_reader_batch=-1,
                            ingest_ring_slots=-8)
    neg.apply_defaults()
    assert (neg.ingest_reader_shards, neg.ingest_reader_batch,
            neg.ingest_ring_slots) == (0, 0, 0)

    for knob, val in (("ingest_simd", "neon"),
                      ("ingest_backend", "epoll")):
        bad = config_mod.Config(**{knob: val})
        with pytest.raises(ValueError, match=knob):
            bad.apply_defaults()


def test_sink_filtering():
    from veneur_tpu import sinks as sink_mod
    from veneur_tpu.samplers.samplers import InterMetric
    from veneur_tpu.util.matcher import TagMatcher
    spec = sink_mod.SinkSpec(
        kind="x", name="x", max_name_length=10, max_tags=2,
        strip_tags=[TagMatcher(kind="prefix", value="secret")],
        add_tags={"env": "prod"})
    ms = [
        InterMetric("ok", 0, 1, ["a:1", "secret:x"], "counter"),
        InterMetric("waytoolongname", 0, 1, [], "counter"),
        InterMetric("manytags", 0, 1, ["a:1", "b:2", "c:3"], "counter"),
    ]
    out, counts = sink_mod.filter_metrics_for_sink(spec, False, ms)
    assert [m.name for m in out] == ["ok"]
    assert out[0].tags == ["a:1", "env:prod"]
    assert counts["max_name_length"] == 1
    assert counts["max_tags"] == 1
    # original untouched (sinks must not mutate shared metrics)
    assert ms[0].tags == ["a:1", "secret:x"]


def test_matcher_semantics():
    from veneur_tpu.util import matcher as mm
    cfgs = [mm.Matcher(
        name=mm.NameMatcher(kind="prefix", value="api."),
        tags=[mm.TagMatcher(kind="exact", value="env:prod"),
              mm.TagMatcher(kind="prefix", value="canary", unset=True)])]
    assert mm.match(cfgs, "api.hits", ["env:prod"])
    assert not mm.match(cfgs, "web.hits", ["env:prod"])
    assert not mm.match(cfgs, "api.hits", ["env:dev"])
    assert not mm.match(cfgs, "api.hits", ["env:prod", "canary:true"])


def test_http_debug_profile(fixture_server):
    """JAX profiler trace endpoint (SURVEY §5.1 analog of pprof)."""
    import json as json_mod

    srv, _ = fixture_server(enable_profiling=True)
    api = http_api.HttpApi(srv, "127.0.0.1:0")
    api.start()
    host, port = api.address
    base = f"http://{host}:{port}"
    body = urllib.request.urlopen(
        base + "/debug/profile?seconds=0.2", timeout=30).read()
    out = json_mod.loads(body)
    assert out["files"] > 0 and "veneur-jax-trace-" in out["trace_dir"]
    api.stop()


def test_http_debug_profile_disabled(fixture_server):
    srv, _ = fixture_server()  # enable_profiling defaults off
    api = http_api.HttpApi(srv, "127.0.0.1:0")
    api.start()
    host, port = api.address
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            f"http://{host}:{port}/debug/profile", timeout=10)
    assert exc.value.code == 403
    api.stop()


def test_tags_exclude_per_sink(fixture_server):
    """tags_exclude: bare keys strip everywhere; "key|sinkname" strips for
    that sink only (setSinkExcludedTags, server.go:660,1456-1463)."""
    srv, sink = fixture_server(tags_exclude=["nonce", "region|channel"])
    # the fixture's channel sink is named "channel"
    _, addr = srv.statsd_addrs[0]
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.sendto(b"te.m:1|c|#nonce:abc,region:us,keep:yes", addr)
    s.close()
    deadline = time.time() + 5
    while time.time() < deadline and srv.aggregator.processed < 1:
        time.sleep(0.05)
        srv._drain_native()
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "te.m" for m in a))
    m = [x for x in ms if x.name == "te.m"][0]
    assert m.tags == ["keep:yes"], m.tags


def test_grpc_listen_addresses_edge_ingest(fixture_server):
    """grpc_listen_addresses hosts SSF SendSpan + dogstatsd SendPacket on
    any instance (StartGRPC, networking.go:326-391) WITHOUT the Forward
    import service (that is grpc_address's global-tier job)."""
    import grpc as grpc_mod

    from veneur_tpu.core.server import _SpanSinkWorker
    from veneur_tpu.protocol import (dogstatsd_grpc_pb2, metric_pb2,
                                     ssf_pb2)
    from veneur_tpu.sinks.simple import ChannelSpanSink

    span_sink = ChannelSpanSink()
    srv, sink = fixture_server(
        grpc_listen_addresses=["tcp://127.0.0.1:0"])
    srv.span_sinks.append(span_sink)
    srv.span_workers.append(
        _SpanSinkWorker(span_sink, 100, 1, srv._shutdown))
    port = srv.grpc_ingest_listeners[0].port
    channel = grpc_mod.insecure_channel(f"127.0.0.1:{port}")

    # dogstatsd bytes over gRPC
    send_packet = channel.unary_unary(
        "/dogstatsd.DogstatsdGRPC/SendPacket",
        request_serializer=(
            dogstatsd_grpc_pb2.DogstatsdPacket.SerializeToString),
        response_deserializer=dogstatsd_grpc_pb2.Empty.FromString)
    send_packet(dogstatsd_grpc_pb2.DogstatsdPacket(
        packetBytes=b"grpc.edge:11|c"), timeout=5)

    # SSF span over gRPC
    send_span = channel.unary_unary(
        "/ssf.SSFGRPC/SendSpan",
        request_serializer=ssf_pb2.SSFSpan.SerializeToString,
        response_deserializer=lambda b: b)
    send_span(ssf_pb2.SSFSpan(version=0, trace_id=5, id=6, name="eop",
                              service="svc", start_timestamp=1,
                              end_timestamp=2), timeout=5)

    # the Forward service must NOT be served on this listener
    v2 = channel.stream_unary(
        "/forwardrpc.Forward/SendMetricsV2",
        request_serializer=metric_pb2.Metric.SerializeToString,
        response_deserializer=lambda b: b)
    with pytest.raises(grpc_mod.RpcError) as exc:
        v2(iter([metric_pb2.Metric(name="x")]), timeout=5)
    assert exc.value.code() == grpc_mod.StatusCode.UNIMPLEMENTED

    # grpc.health.v1 probe (networking.go:377-384 analog)
    health = channel.unary_unary(
        "/grpc.health.v1.Health/Check",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b)
    assert health(b"", timeout=5) == b"\x08\x01"  # status=SERVING

    # received-per-protocol accounting for both gRPC ingest kinds
    assert srv.proto_received["dogstatsd-grpc"] == 1
    assert srv.proto_received["ssf-grpc"] == 1

    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "grpc.edge" for m in a))
    assert [m for m in ms if m.name == "grpc.edge"][0].value == 11.0
    got_span = span_sink.queue.get(timeout=5)
    assert got_span.name == "eop"
    channel.close()


@pytest.mark.skipif(
    subprocess.run(["which", "openssl"], capture_output=True).returncode != 0,
    reason="openssl unavailable")
def test_grpc_ingest_listener_honors_tls(fixture_server, tmp_path):
    """With server TLS configured, the edge gRPC listener serves mTLS
    (networking.go:363-374) — plaintext clients are rejected."""
    import grpc as grpc_mod

    from veneur_tpu.protocol import dogstatsd_grpc_pb2

    ca, certs = _make_certs(tmp_path)
    skey, scrt = certs["server"]
    ckey, ccrt = certs["client"]
    srv, sink = fixture_server(
        grpc_listen_addresses=["tcp://127.0.0.1:0"],
        tls_key=skey, tls_certificate=scrt,
        tls_authority_certificate=ca)
    port = srv.grpc_ingest_listeners[0].port

    def send(channel):
        rpc = channel.unary_unary(
            "/dogstatsd.DogstatsdGRPC/SendPacket",
            request_serializer=(
                dogstatsd_grpc_pb2.DogstatsdPacket.SerializeToString),
            response_deserializer=dogstatsd_grpc_pb2.Empty.FromString)
        rpc(dogstatsd_grpc_pb2.DogstatsdPacket(
            packetBytes=b"grpc.tls:3|c"), timeout=5)

    # plaintext must fail
    with pytest.raises(grpc_mod.RpcError):
        ch = grpc_mod.insecure_channel(f"127.0.0.1:{port}")
        send(ch)
    # mTLS client works
    with open(ca, "rb") as f:
        ca_b = f.read()
    with open(ckey, "rb") as f:
        key_b = f.read()
    with open(ccrt, "rb") as f:
        crt_b = f.read()
    creds = grpc_mod.ssl_channel_credentials(
        root_certificates=ca_b, private_key=key_b, certificate_chain=crt_b)
    ch = grpc_mod.secure_channel(f"127.0.0.1:{port}", creds)
    send(ch)
    ch.close()
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "grpc.tls" for m in a))
    assert [m for m in ms if m.name == "grpc.tls"][0].value == 3.0


def test_grpc_health_unknown_service_not_found(fixture_server):
    import grpc as grpc_mod

    srv, _ = fixture_server(grpc_listen_addresses=["tcp://127.0.0.1:0"])
    port = srv.grpc_ingest_listeners[0].port
    ch = grpc_mod.insecure_channel(f"127.0.0.1:{port}")
    health = ch.unary_unary("/grpc.health.v1.Health/Check",
                            request_serializer=lambda b: b,
                            response_deserializer=lambda b: b)
    assert health(b"", timeout=5) == b"\x08\x01"
    # service name "veneur" (field 1, len 6): SERVING
    assert health(b"\x0a\x06veneur", timeout=5) == b"\x08\x01"
    with pytest.raises(grpc_mod.RpcError) as exc:
        health(b"\x0a\x04nope", timeout=5)
    assert exc.value.code() == grpc_mod.StatusCode.NOT_FOUND
    ch.close()


def test_grpc_ingest_half_tls_config_fails_loud(tmp_path):
    cfg = make_config(grpc_listen_addresses=["tcp://127.0.0.1:0"],
                      tls_key=str(tmp_path / "only.key"))
    srv = Server(cfg)
    with pytest.raises(ValueError, match="both"):
        srv.start()
    srv.shutdown()


def test_ipv6_udp_listener(fixture_server):
    """udp://[::1]:0 binds an AF_INET6 listener and ingests normally
    (the reference resolves either address family)."""
    srv, sink = fixture_server(
        statsd_listen_addresses=["udp://[::1]:0"])
    kind, addr = srv.statsd_addrs[0]
    s = socket.socket(socket.AF_INET6, socket.SOCK_DGRAM)
    s.sendto(b"v6.c:6|c", (addr[0], addr[1]))
    s.close()
    deadline = time.time() + 5
    while time.time() < deadline and srv.aggregator.processed < 1:
        time.sleep(0.05)
        srv._drain_native()
    srv.flush()
    ms = drain_until(sink, lambda a: any(m.name == "v6.c" for m in a))
    assert [m for m in ms if m.name == "v6.c"][0].value == 6.0
