"""Tag-dimensional analytics (ISSUE 17): group-by sketch cubes.

Covers the cube subsystem end to end at tier-1 speed:

  * dimension/identity contracts — sorted tag values make
    ``group_by=b,a`` and ``group_by=a,b`` the same group on every tier
  * the per-dimension group budget: admission, accounted overflow into
    ``veneur.cube.other``, conservation counters, promotion at interval
    boundaries (with the evict-fault abort), checkpoint roundtrip
  * the segmented-reduce kernel: interpret-mode parity against the XLA
    twin and bit-identical sums across row tilings
  * the query surface: group_by order-independence, payload= knob,
    top-k-by-quantile, batched group quantile eval parity
  * 3-tier conservation cells for BOTH families (tdigest via the
    cube-storm chaos arm, moments via a dedicated cluster) — exact
    per-group counts with visibly accounted overflow
  * the measured resident-link probe's cached path (satellite a)
"""
from __future__ import annotations

import numpy as np
import pytest

from veneur_tpu.cubes import cube as cb
from veneur_tpu.cubes.cube import (CUBE_TAG, DIM_TAG_PREFIX, OTHER_NAME,
                                   CubeDimension, CubeMaintainer,
                                   match_dimension, parse_dimensions,
                                   project_group)
from veneur_tpu.samplers.metric_key import MetricKey, MetricScope


# ---------------------------------------------------------------------------
# dimensions & identities
# ---------------------------------------------------------------------------

def test_dimension_tags_sorted_and_id_order_independent():
    a = CubeDimension(["region", "endpoint"])
    b = CubeDimension(["endpoint", "region"])
    assert a.tags == b.tags == ("endpoint", "region")
    assert a.dim_id == b.dim_id
    # name-gated siblings get DISTINCT ids (distinct budgets/other rows)
    g = CubeDimension(["endpoint", "region"], "api.*")
    assert g.dim_id != a.dim_id
    assert g.matches_name("api.latency")
    assert not g.matches_name("web.latency")
    assert a.matches_name("anything")


def test_dimension_validation():
    with pytest.raises(ValueError):
        CubeDimension([])
    with pytest.raises(ValueError):
        CubeDimension(["a:b"])          # ':' forbidden in tag names
    with pytest.raises(ValueError):
        CubeDimension(["a,b"])          # ',' forbidden in tag names
    with pytest.raises(ValueError):
        CubeDimension(["a", "a"])       # repeated tag name
    with pytest.raises(ValueError):
        parse_dimensions("region")      # not a list
    with pytest.raises(ValueError):
        parse_dimensions([{"tags": ["a"], "bogus": 1}])
    with pytest.raises(ValueError):
        parse_dimensions([["a", "b"], ["b", "a"]])   # duplicate dim
    dims = parse_dimensions([["region"],
                             {"tags": ["endpoint", "region"],
                              "match": "api.*"}])
    assert [d.tags for d in dims] == [("region",),
                                      ("endpoint", "region")]


def test_dimension_extract_requires_all_tags():
    d = CubeDimension(["endpoint", "region"])
    assert d.extract(["endpoint:/a", "host:h1", "region:r1"]) == \
        ["endpoint:/a", "region:r1"]
    # partial tag sets never smear into a group
    assert d.extract(["endpoint:/a", "host:h1"]) is None
    # first occurrence wins for duplicated names (sorted wire form)
    assert d.extract(["endpoint:/a", "endpoint:/z", "region:r1"]) == \
        ["endpoint:/a", "region:r1"]


def test_group_identity_is_order_independent():
    k1, s1, t1 = CubeMaintainer.group_identity(
        "api.latency", "histogram", ["region:r1", "endpoint:/a"],
        MetricScope.GLOBAL_ONLY)
    k2, s2, t2 = CubeMaintainer.group_identity(
        "api.latency", "histogram", ["endpoint:/a", "region:r1"],
        MetricScope.GLOBAL_ONLY)
    assert k1 == k2 and t1 == t2 and s1 == s2
    assert CUBE_TAG in t1 and t1 == sorted(t1)


def test_project_group_strips_markers_and_projects():
    jt = ",".join(sorted(["endpoint:/a", "region:r1", CUBE_TAG]))
    assert project_group(jt, ["region"]) == \
        ",".join(sorted(["region:r1", CUBE_TAG]))
    # marker tags never leak into the projected identity
    jt2 = ",".join(sorted(["region:r1", CUBE_TAG,
                           DIM_TAG_PREFIX + "endpoint|region"]))
    assert project_group(jt2, ["region"]) == \
        ",".join(sorted(["region:r1", CUBE_TAG]))


def test_match_dimension_exact_superset_and_name_gate():
    dims = parse_dimensions([
        {"tags": ["endpoint", "region"], "match": "api.*"},
        ["az", "endpoint", "region"],
    ])
    d, exact = match_dimension(dims, ["region", "endpoint"], "api.x")
    assert exact and d is dims[0]
    # the glob gate hides the exact dimension for other names: the
    # ungated 3-tag superset answers via coarsening
    d, exact = match_dimension(dims, ["region", "endpoint"], "web.x")
    assert not exact and d is dims[1]
    # smallest superset wins
    d, exact = match_dimension(dims, ["region"], "web.x")
    assert not exact and d is dims[1]
    assert match_dimension(dims, ["host"], "api.x") is None


# ---------------------------------------------------------------------------
# maintainer: budget, overflow, promotion, checkpoint
# ---------------------------------------------------------------------------

def _hkey(name="api.latency"):
    return MetricKey(name, "histogram", "")


def test_maintainer_admission_overflow_and_conservation():
    dims = parse_dimensions([["endpoint"]])
    m = CubeMaintainer(dims, group_budget=2, seed=1)
    sc = MetricScope.GLOBAL_ONLY
    out_a = m.rollups(_hkey(), sc, ["endpoint:/a", "host:h1"], n=3)
    out_b = m.rollups(_hkey(), sc, ["endpoint:/b", "host:h2"], n=2)
    assert [k.name for k, _, _ in out_a] == ["api.latency"]
    assert CUBE_TAG in out_a[0][2]
    # third distinct group: over budget, degrades to the other row
    out_c = m.rollups(_hkey(), sc, ["endpoint:/c"], n=5)
    assert [k.name for k, _, _ in out_c] == [OTHER_NAME]
    assert any(t.startswith(DIM_TAG_PREFIX) for t in out_c[0][2])
    snap = m.snapshot()
    assert snap["groups"] == 2
    assert snap["overflowed"] == 5
    assert snap["rollup_points"] == 10       # 3 + 2 + 5: nothing lost
    assert snap["groups_admitted"] == 2
    # tag-mismatched and name-mismatched samples produce no rollups
    assert m.rollups(_hkey(), sc, ["host:h1"]) == []
    gated = CubeMaintainer(parse_dimensions(
        [{"tags": ["endpoint"], "match": "api.*"}]), 2)
    assert gated.rollups(_hkey("web.x"), sc, ["endpoint:/a"]) == []
    # cube rows themselves never cube again (no double count)
    assert m.rollups(out_a[0][0], sc, list(out_a[0][2])) == []
    assert m.rollups(_hkey(), sc,
                     ["endpoint:/a", "veneur_rollup:t"]) == []


def test_maintainer_end_interval_promotes_hot_candidate():
    m = CubeMaintainer(parse_dimensions([["endpoint"]]),
                       group_budget=1, seed=2)
    sc = MetricScope.GLOBAL_ONLY
    m.rollups(_hkey(), sc, ["endpoint:/cold"], n=1)
    for _ in range(5):
        m.rollups(_hkey(), sc, ["endpoint:/hot"], n=1)
    evicted: list = []
    m.end_interval(evicted.extend)
    assert len(evicted) == 1 and evicted[0][0].name == "api.latency"
    assert "endpoint:/cold" in evicted[0][0].joined_tags
    snap = m.snapshot()
    assert snap["groups_evicted"] == 1 and snap["groups"] == 1
    # the hot group is now exact
    out = m.rollups(_hkey(), sc, ["endpoint:/hot"])
    assert out[0][0].name == "api.latency"


def test_maintainer_evict_fault_aborts_with_membership_untouched():
    m = CubeMaintainer(parse_dimensions([["endpoint"]]),
                       group_budget=1, seed=2)
    sc = MetricScope.GLOBAL_ONLY
    m.rollups(_hkey(), sc, ["endpoint:/cold"], n=1)
    for _ in range(5):
        m.rollups(_hkey(), sc, ["endpoint:/hot"], n=1)
    epoch = m.epoch

    def boom(keys):
        raise RuntimeError("arena.evict fault")

    with pytest.raises(RuntimeError):
        m.end_interval(boom)
    # the pass aborted BEFORE touching membership: cold is still exact
    assert m.epoch == epoch and m.snapshot()["groups_evicted"] == 0
    out = m.rollups(_hkey(), sc, ["endpoint:/cold"])
    assert out[0][0].name == "api.latency"


def test_maintainer_checkpoint_roundtrip():
    dims = parse_dimensions([["endpoint"]])
    m = CubeMaintainer(dims, group_budget=2, seed=3)
    sc = MetricScope.GLOBAL_ONLY
    m.rollups(_hkey(), sc, ["endpoint:/a"], n=4)
    m.rollups(_hkey(), sc, ["endpoint:/b"], n=1)
    m.rollups(_hkey(), sc, ["endpoint:/c"], n=1)   # overflow
    state = m.checkpoint_state()
    m2 = CubeMaintainer(dims, group_budget=2, seed=3)
    m2.restore_state(state)
    s1, s2 = m.snapshot(), m2.snapshot()
    assert s2["groups"] == s1["groups"] == 2
    assert s2["rollup_points"] == s1["rollup_points"]
    assert s2["overflowed"] == s1["overflowed"]
    # membership restored: the known groups stay exact, a new one
    # still overflows (budget full)
    admitted_before = s2["groups_admitted"]
    assert m2.rollups(_hkey(), sc,
                      ["endpoint:/a"])[0][0].name == "api.latency"
    assert m2.rollups(_hkey(), sc,
                      ["endpoint:/d"])[0][0].name == OTHER_NAME
    assert m2.snapshot()["groups_admitted"] == admitted_before


def test_maintainer_top_groups_deterministic_tie_break():
    m = CubeMaintainer(parse_dimensions([["endpoint"]]),
                       group_budget=4, seed=7)
    sc = MetricScope.GLOBAL_ONLY
    for ep, n in (("/a", 2), ("/b", 5), ("/c", 2), ("/d", 1)):
        m.rollups(_hkey(), sc, [f"endpoint:{ep}"], n=n)
    top = m.top_groups(0, 3)
    assert top[0][0].joined_tags.find("endpoint:/b") >= 0
    # the tied pair orders by the seeded rank — stable across calls
    assert m.top_groups(0, 3) == top


# ---------------------------------------------------------------------------
# segmented reduce: interpret parity + tiling bit-identity
# ---------------------------------------------------------------------------

def _seg_case(u, c, g, seed):
    rng = np.random.default_rng(seed)
    vals = rng.normal(size=(u, c)).astype(np.float32)
    seg = np.sort(rng.integers(0, g, size=u)).astype(np.int32)
    return vals, seg


@pytest.mark.parametrize("u,c,g", [(8, 128, 3), (64, 128, 9),
                                   (96, 256, 17)])
def test_segment_sums_interpret_parity_with_twin(u, c, g):
    import jax.numpy as jnp

    from veneur_tpu.ops import segmented_reduce as sr
    vals, seg = _seg_case(u, c, g, seed=u + c)
    got = np.asarray(sr.segment_sums(
        jnp.asarray(vals), jnp.asarray(seg), g, interpret=True))
    want = np.asarray(sr._segment_sums_twin(
        jnp.asarray(vals), jnp.asarray(seg), g))[:g]
    assert got.shape == (g, c)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_segment_sums_bit_identical_across_tilings(monkeypatch):
    import jax.numpy as jnp

    from veneur_tpu.ops import segmented_reduce as sr
    # adversarial values: mixed magnitudes make f32 addition order
    # visible, so any tiling-dependent reassociation fails exactly
    rng = np.random.default_rng(11)
    vals = (rng.normal(size=(64, 128))
            * 10.0 ** rng.integers(-3, 4, size=(64, 128))
            ).astype(np.float32)
    seg = np.sort(rng.integers(0, 5, size=64)).astype(np.int32)
    outs = []
    for tile in (8, 16, 32, 64):
        monkeypatch.setattr(sr, "_row_tile", lambda u, t=tile: t)
        outs.append(np.asarray(sr.segment_sums(
            jnp.asarray(vals), jnp.asarray(seg), 5, interpret=True)))
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)   # BIT-identical, not close


def test_coarsen_moments_vectors_matches_union_sketch():
    from veneur_tpu.ops import segmented_reduce as sr
    from veneur_tpu.sketches import moments as mo
    rng = np.random.default_rng(5)
    k = mo.DEFAULT_K
    # 2 coarse groups x 3 fine members each, distinct per-member ranges
    hashes, vecs, want = [], [], {}
    for gh in (np.uint64(7), np.uint64(9)):
        union = mo.MomentsSketch(k)
        for j in range(3):
            s = mo.MomentsSketch(k)
            s.add_batch(rng.gamma(2.0, 10.0 * (j + 1), 50))
            union.merge(s)
            vecs.append(s.vec)
            hashes.append(gh)
        want[int(gh)] = union.vec
    uniq, out, launch = sr.coarsen_moments_vectors(
        np.stack(vecs), np.asarray(hashes, np.uint64))
    assert launch == 2 and list(uniq) == [7, 9]
    for i, gh in enumerate(uniq):
        w = want[int(gh)]
        # non-additive envelope + count/sum: exact
        assert out[i, mo.IDX_COUNT] == w[mo.IDX_COUNT]
        assert out[i, mo.IDX_MIN] == w[mo.IDX_MIN]
        assert out[i, mo.IDX_MAX] == w[mo.IDX_MAX]
        np.testing.assert_allclose(out[i, mo.IDX_SUM], w[mo.IDX_SUM],
                                   rtol=1e-6)
        # rebased power sums travel through the f32 kernel: close
        np.testing.assert_allclose(out[i], w, rtol=5e-4, atol=5e-4)
        # and the solved quantiles agree with the union sketch's
        got_q = mo.MomentsSketch(k)
        got_q.vec = out[i]
        span = w[mo.IDX_MAX] - w[mo.IDX_MIN]
        uq = mo.MomentsSketch(k)
        uq.vec = w
        for q in (0.5, 0.99):
            assert abs(got_q.quantile(q) - uq.quantile(q)) < 0.05 * span


# ---------------------------------------------------------------------------
# query surface: order independence, payload knob, top-k
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cube_server():
    from veneur_tpu import config as config_mod
    from veneur_tpu.core.server import Server
    from veneur_tpu.samplers import samplers as sm
    from veneur_tpu.samplers.metric_key import UDPMetric
    cfg = config_mod.Config(
        interval=10.0, percentiles=[0.5, 0.99],
        hostname="cube-test", trace_flush_enabled=False,
        query_window_slots=4,
        cube_dimensions=[{"tags": ["endpoint", "region"],
                          "match": "cs.*"}],
        cube_group_budget=4, cube_seed=7)
    srv = Server(cfg)
    srv.start()
    try:
        rng = np.random.default_rng(13)
        batch = []
        # 4 exact groups x 30 samples, then 2 over-budget groups x 5
        for gi, (ep, rg, n) in enumerate(
                [("/a", "r0", 30), ("/a", "r1", 30), ("/b", "r0", 30),
                 ("/b", "r1", 30), ("/ov0", "r9", 5), ("/ov1", "r9", 5)]):
            for v in rng.gamma(2.0, 10.0 * (gi + 1), n):
                tags = sorted([f"endpoint:{ep}", f"region:{rg}",
                               "host:h1"])
                batch.append(UDPMetric(
                    name="cs.load", type=sm.TYPE_HISTOGRAM,
                    joined_tags=",".join(tags), value=float(v),
                    tags=tags, scope=MetricScope.GLOBAL_ONLY))
        srv.aggregator.process_batch(batch)
        srv.aggregator.sync_staged(min_samples=1)
        srv.flush()
        yield srv
    finally:
        srv.shutdown()


def _q(srv, **params):
    return srv.query.serve({k: [str(v)] for k, v in params.items()})


def test_engine_group_by_order_independent(cube_server):
    code1, r1 = _q(cube_server, name="cs.load",
                   group_by="endpoint,region", q="0.5,0.99", slots=1)
    code2, r2 = _q(cube_server, name="cs.load",
                   group_by="region,endpoint", q="0.5,0.99", slots=1)
    assert code1 == code2 == 200
    assert r1["groups_total"] == r2["groups_total"] == 4
    g1 = {g["key"]: g for g in r1["groups"]}
    g2 = {g["key"]: g for g in r2["groups"]}
    assert g1.keys() == g2.keys()
    for key in g1:
        assert g1[key]["count"] == g2[key]["count"]
        assert g1[key]["quantiles"] == g2[key]["quantiles"]
    # overflow stays visibly accounted on the query plane too
    assert r1["other"] and r1["other"]["count"] == 10.0


def test_engine_payload_knob(cube_server):
    code, full = _q(cube_server, name="cs.load",
                    group_by="endpoint,region", q="0.5", slots=1)
    assert code == 200
    assert all(g["payload"] for g in full["groups"])
    code, lean = _q(cube_server, name="cs.load",
                    group_by="endpoint,region", q="0.5", slots=1,
                    payload=0)
    assert code == 200
    assert all(g["payload"] is None for g in lean["groups"])
    assert lean["other"]["payload"] is None
    # quantiles/counts identical either way — payload= is wire-size only
    assert {g["key"]: g["quantiles"] for g in lean["groups"]} == \
        {g["key"]: g["quantiles"] for g in full["groups"]}
    code, err = _q(cube_server, name="cs.load", q="0.5", slots=1,
                   payload="maybe")
    assert code == 400


def test_engine_top_k_by_quantile(cube_server):
    code, r = _q(cube_server, name="cs.load",
                 group_by="endpoint,region", q="0.99", slots=1,
                 top=2, by="q99")
    assert code == 200
    assert len(r["groups"]) == 2 and r["groups_total"] == 4
    q99 = [g["quantiles"]["0.99"] for g in r["groups"]]
    assert q99 == sorted(q99, reverse=True)
    # the full answer's best two are exactly these
    _, full = _q(cube_server, name="cs.load",
                 group_by="endpoint,region", q="0.99", slots=1)
    best = sorted((g["quantiles"]["0.99"] for g in full["groups"]),
                  reverse=True)[:2]
    assert q99 == best


def test_weighted_quantiles_np_batch_parity():
    from veneur_tpu.query.engine import (weighted_quantiles_np,
                                         weighted_quantiles_np_batch)
    rng = np.random.default_rng(23)
    qs = np.array([0.1, 0.5, 0.99])
    for _ in range(40):
        n_g = int(rng.integers(1, 8))
        vals, wts, mins, maxs = [], [], [], []
        for _ in range(n_g):
            n = int(rng.integers(0, 40))
            v = rng.normal(size=n) * 10
            w = np.where(rng.random(n) < 0.15, 0.0, rng.random(n) + 0.1)
            vals.append(v)
            wts.append(w)
            lo = float(v[w > 0].min()) if (w > 0).any() else 0.0
            hi = float(v[w > 0].max()) if (w > 0).any() else 0.0
            mins.append(lo)
            maxs.append(hi)
        got = weighted_quantiles_np_batch(vals, wts, mins, maxs, qs)
        for g in range(n_g):
            want = weighted_quantiles_np(vals[g], wts[g], mins[g],
                                         maxs[g], qs)
            if want is None:
                assert got[g] is None
            else:
                np.testing.assert_allclose(got[g], want,
                                           rtol=1e-9, atol=1e-9)


# ---------------------------------------------------------------------------
# 3-tier conservation cells (both families)
# ---------------------------------------------------------------------------

def test_cube_storm_cell_overflow_accounted_end_to_end():
    """Fast tdigest-family cell: the cube-storm chaos arm drives pinned
    + over-budget groups through locals -> globals -> proxy and gates
    exact conservation on BOTH planes (emission and query)."""
    from veneur_tpu.testbed.chaos import arm_by_name, run_chaos_arm
    row = run_chaos_arm(arm_by_name("cube-storm"), seed=3)
    assert row["ok"], row
    assert row["fired"] > 0                      # overflow actually hit
    assert row["conserved"]
    assert row["under_budget"]
    assert row["routing_exclusive"]
    assert row["local_emission_exact"]
    assert row["query_plane_exact"]
    assert row["query_envelope_ok"]
    assert row["counter_deficit"] == 0.0


@pytest.mark.slow
def test_three_tier_cube_conservation_moments_family():
    """Moments-family conservation through all three tiers, plus the
    order-independence regression at the cluster level: the proxy's
    scatter-gather answer for ``group_by=b,a`` equals ``a,b``."""
    from veneur_tpu.testbed import verify
    from veneur_tpu.testbed.cluster import Cluster, ClusterSpec
    from veneur_tpu.testbed.traffic import CubeGen, TrafficGen
    # pin_samples=80: at 3 intervals every group carries 240 samples,
    # enough that the maxent solver's q99 sits well inside the moments
    # envelope for ANY seed (swept; 40/group is seed-marginal)
    gen = CubeGen(seed=5, moments=True, pin_samples=80)
    spec = ClusterSpec(n_locals=2, n_globals=2, query_api=True,
                       discovery_interval_s=0.2,
                       cube_dimensions=(gen.dimension(),),
                       cube_group_budget=gen.budget,
                       cube_seed=10,
                       sketch_family_rules=(TrafficGen.MOMENTS_RULE,))
    cluster = Cluster(spec)
    loc: list = []
    intervals = 3
    try:
        cluster.start()
        for _ in range(intervals):
            cluster.run_interval(gen.next_interval(2))
            loc.append(cluster.drain_local_sinks())
        addr = cluster.proxy_http_addr()
        resp = Cluster.query_http(addr, name=gen.name,
                                  group_by="region,endpoint",
                                  q="0.5,0.99", slots=intervals)
        swapped = Cluster.query_http(addr, name=gen.name,
                                     group_by="endpoint,region",
                                     q="0.5,0.99", slots=intervals)
    finally:
        cluster.stop()

    local_check = verify.check_cube_counts(gen, loc)
    assert local_check["ok"], local_check
    query_check = verify.check_cube_query(gen, resp, intervals,
                                          percentiles=[0.5, 0.99])
    assert query_check["ok"], query_check
    assert {g["key"]: (g["count"], g["quantiles"])
            for g in resp["groups"]} == \
        {g["key"]: (g["count"], g["quantiles"])
         for g in swapped["groups"]}


# ---------------------------------------------------------------------------
# resident link probe (satellite a)
# ---------------------------------------------------------------------------

@pytest.fixture
def link_probe_state():
    from veneur_tpu.parallel import serving
    saved = dict(serving._LINK_PROBE)
    serving._LINK_PROBE.clear()
    serving._LINK_PROBE.update({"measured": False, "probes": 0})
    yield serving._LINK_PROBE
    serving._LINK_PROBE.clear()
    serving._LINK_PROBE.update(saved)


def test_resident_link_probe_measures_exactly_once(monkeypatch,
                                                   link_probe_state):
    from veneur_tpu.parallel import serving
    monkeypatch.delenv("VENEUR_TPU_RESIDENT_LINK", raising=False)
    calls = []

    def fake_measure():
        calls.append(1)
        return {"ok": True, "backend": "cpu", "resident_us": 1.0,
                "staged_us": 10.0, "forced": False}

    monkeypatch.setattr(serving, "_measure_link_probe", fake_measure)
    assert serving.resident_link_ok() is True
    # the cached path: NO second measurement, probes stays at 1
    assert serving.resident_link_ok() is True
    assert serving.resident_link_ok() is True
    assert len(calls) == 1
    stats = serving.link_probe_stats()
    assert stats["measured"] is True and stats["probes"] == 1
    assert stats["resident_us"] == 1.0
    # stats is a COPY: /debug/vars readers cannot poison the cache
    stats["ok"] = False
    assert serving.resident_link_ok() is True


def test_resident_link_probe_env_pin_skips_measurement(monkeypatch,
                                                       link_probe_state):
    from veneur_tpu.parallel import serving

    def boom():
        raise AssertionError("pinned probe must not measure")

    monkeypatch.setattr(serving, "_measure_link_probe", boom)
    monkeypatch.setenv("VENEUR_TPU_RESIDENT_LINK", "0")
    assert serving.resident_link_ok() is False
    stats = serving.link_probe_stats()
    assert stats["forced"] is True and stats["measured"] is True
    # the pin caches like a measurement
    assert serving.resident_link_ok() is False


def test_link_probe_stats_never_forces_measurement(link_probe_state):
    from veneur_tpu.parallel import serving
    stats = serving.link_probe_stats()
    assert stats["measured"] is False and stats["probes"] == 0
    # still unmeasured after the read
    assert serving._LINK_PROBE["measured"] is False
