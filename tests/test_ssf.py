"""SSF pipeline tests: framing round-trips and error poisoning
(protocol/wire_test.go), SSF->metric conversion (parser_test.go SSF cases),
span e2e over real sockets with metric extraction
(server_test.go:1240 SSF e2e), trace client loopback."""

import io
import socket
import struct
import time

import pytest

from veneur_tpu import config as config_mod
from veneur_tpu import ssf as ssf_mod
from veneur_tpu import trace as trace_mod
from veneur_tpu.core.server import Server
from veneur_tpu.samplers import ssf_convert
from veneur_tpu.samplers.metric_key import MetricScope
from veneur_tpu.samplers.parser import Parser
from veneur_tpu.sinks import simple as simple_sinks

P = Parser()


def make_span(**kw):
    span = ssf_mod.SSFSpan(
        version=0, trace_id=1, id=2, parent_id=0,
        start_timestamp=1_000_000_000, end_timestamp=2_000_000_000,
        service="svc", name="op")
    for k, v in kw.items():
        setattr(span, k, v)
    return span


def test_frame_roundtrip():
    span = make_span()
    span.metrics.append(ssf_mod.count("hits", 3, {"a": "b"}))
    buf = io.BytesIO()
    ssf_mod.write_ssf(buf, span)
    buf.seek(0)
    back = ssf_mod.read_ssf(buf)
    assert back.name == "op"
    assert back.metrics[0].name == "hits"
    assert back.metrics[0].sample_rate == 1.0  # normalized from 0
    assert ssf_mod.read_ssf(buf) is None  # clean EOF


def test_frame_version_error():
    buf = io.BytesIO(b"\x01\x00\x00\x00\x05hello")
    with pytest.raises(ssf_mod.FrameVersionError):
        ssf_mod.read_ssf(buf)


def test_frame_length_error():
    buf = io.BytesIO(struct.pack(">BI", 0, ssf_mod.MAX_SSF_PACKET_LENGTH + 1))
    with pytest.raises(ssf_mod.FrameLengthError):
        ssf_mod.read_ssf(buf)


def test_frame_truncation_error():
    span = make_span()
    data = ssf_mod.frame_bytes(span)
    buf = io.BytesIO(data[:-3])
    with pytest.raises(ssf_mod.FramingIOError):
        ssf_mod.read_ssf(buf)


def test_name_tag_normalization():
    span = ssf_mod.SSFSpan(trace_id=1, id=2)
    span.tags["name"] = "from-tag"
    back = ssf_mod.parse_ssf(span.SerializeToString())
    assert back.name == "from-tag"
    assert "name" not in back.tags


def test_valid_trace():
    assert ssf_mod.valid_trace(make_span())
    assert not ssf_mod.valid_trace(make_span(id=0))
    assert not ssf_mod.valid_trace(make_span(name=""))
    with pytest.raises(ssf_mod.InvalidTrace):
        ssf_mod.validate_trace(make_span(end_timestamp=0))


def test_parse_metric_ssf_types():
    s = ssf_mod.count("c", 2, {"x": "y"})
    m = ssf_convert.parse_metric_ssf(P, s)
    assert (m.type, m.value, m.tags) == ("counter", 2.0, ["x:y"])

    s = ssf_mod.gauge("g", 1.5)
    assert ssf_convert.parse_metric_ssf(P, s).type == "gauge"

    s = ssf_mod.set_sample("s", "member")
    m = ssf_convert.parse_metric_ssf(P, s)
    assert (m.type, m.value) == ("set", "member")

    s = ssf_mod.status("st", ssf_mod.SSFSample.WARNING,
                       message="disk 95% full")
    s.status = ssf_mod.SSFSample.WARNING
    m = ssf_convert.parse_metric_ssf(P, s)
    assert (m.type, m.value) == ("status", 1)
    # the service-check message must survive SSF conversion, matching the
    # DogStatsD _sc path (parser.go:290-345)
    assert m.message == "disk 95% full"


def test_span_finish_idempotent():
    """Explicit finish() inside a with-block must not double-submit."""
    spans = []
    client = trace_mod.new_channel_client(spans.append)
    with client.span("op") as s:
        s.add(ssf_mod.count("x", 1))
        s.finish(error=True)
    client.close()
    assert len(spans) == 1
    assert spans[0].error


def test_parse_metric_ssf_scope_tags():
    s = ssf_mod.count("c", 1, {"veneurglobalonly": "true", "k": "v"})
    m = ssf_convert.parse_metric_ssf(P, s)
    assert m.scope == MetricScope.GLOBAL_ONLY
    assert m.tags == ["k:v"]


def test_convert_metrics_invalid_mixed():
    span = make_span()
    span.metrics.append(ssf_mod.count("good", 1))
    span.metrics.append(ssf_mod.count("", 1))  # invalid: no name
    with pytest.raises(ssf_convert.InvalidMetricsError) as exc:
        ssf_convert.convert_metrics(P, span)
    assert len(exc.value.samples) == 1
    assert [m.name for m in exc.value.metrics] == ["good"]


def test_indicator_conversion():
    span = make_span(indicator=True, error=True)
    span.tags["ssf_objective"] = "checkout"
    ms = ssf_convert.convert_indicator_metrics(
        P, span, "veneur.indicator", "veneur.objective")
    assert len(ms) == 2
    ind, obj = ms
    assert ind.name == "veneur.indicator"
    # SSF has no timer type; Timing() samples parse as histograms
    # (ssf/samples.go Timing -> parser.go:302)
    assert ind.type == "histogram"
    assert ind.value == pytest.approx(1e9)  # 1s in ns
    assert "error:true" in ind.tags
    assert obj.scope == MetricScope.GLOBAL_ONLY
    assert "objective:checkout" in obj.tags

    # non-indicator span is a no-op
    assert ssf_convert.convert_indicator_metrics(
        P, make_span(), "a", "b") == []


def test_span_uniqueness_metrics():
    ms = ssf_convert.convert_span_uniqueness_metrics(P, make_span(), 1.0)
    assert len(ms) == 1
    assert ms[0].type == "set"
    assert ms[0].value == "op"
    assert "service:svc" in ms[0].tags


def _boot_ssf_server(tmp_path, listen):
    cfg = config_mod.Config(
        ssf_listen_addresses=[listen], interval=0.05,
        percentiles=[0.5], aggregates=["count"], hostname="t",
        indicator_span_timer_name="veneur.indicator")
    msink = simple_sinks.ChannelMetricSink()
    ssink = simple_sinks.BlackholeSpanSink()
    srv = Server(cfg, extra_metric_sinks=[msink], extra_span_sinks=[ssink])
    srv.start()
    return srv, msink


def test_ssf_udp_end_to_end():
    srv, msink = _boot_ssf_server(None, "udp://127.0.0.1:0")
    try:
        _, addr = srv.ssf_addrs[0]
        span = make_span(indicator=True)
        span.metrics.append(ssf_mod.count("span.hits", 7, {"q": "r"}))
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.sendto(span.SerializeToString(), addr)
        s.close()
        deadline = time.time() + 5
        while srv.metric_extraction.spans_processed < 1 \
                and time.time() < deadline:
            time.sleep(0.02)
        srv.flush()
        got = []
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            while not msink.queue.empty():
                got.extend(msink.queue.get())
            if not got:
                srv.flush()
                time.sleep(0.05)
        by = {m.name: m for m in got}
        assert by["span.hits"].value == 7.0
        # indicator timer extracted too
        assert "veneur.indicator.count" in by
    finally:
        srv.shutdown()


def test_ssf_unix_stream_end_to_end(tmp_path):
    path = str(tmp_path / "ssf.sock")
    srv, msink = _boot_ssf_server(tmp_path, f"unix://{path}")
    try:
        span = make_span()
        span.metrics.append(ssf_mod.gauge("temp", 70.0))
        c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        c.connect(path)
        c.sendall(ssf_mod.frame_bytes(span))
        c.close()
        deadline = time.time() + 5
        while srv.metric_extraction.spans_processed < 1 \
                and time.time() < deadline:
            time.sleep(0.02)
        srv.flush()
        got = []
        deadline = time.time() + 5
        while time.time() < deadline and not got:
            while not msink.queue.empty():
                got.extend(msink.queue.get())
            if not got:
                srv.flush()
                time.sleep(0.05)
        names = {m.name for m in got}
        assert "temp" in names
        # ssf.names_unique is a randomly-sampled self-metric
        # (convert_span_uniqueness_metrics) and may ride the same
        # flush; nothing else should.
        assert names <= {"temp", "ssf.names_unique"}
    finally:
        srv.shutdown()


def test_trace_client_loopback():
    received = []
    client = trace_mod.new_channel_client(received.append)
    with client.span("op", service="me", indicator=True) as span:
        span.add(ssf_mod.count("inner", 1))
        with span.child("sub"):
            pass
    client.flush()
    time.sleep(0.2)
    assert len(received) == 2  # child finished first, then parent
    names = {s.name for s in received}
    assert names == {"op", "sub"}
    parent = [s for s in received if s.name == "op"][0]
    child = [s for s in received if s.name == "sub"][0]
    assert child.trace_id == parent.trace_id
    assert child.parent_id == parent.id
    assert parent.metrics[0].name == "inner"
    assert ssf_mod.valid_trace(parent)
    client.close()


def test_server_self_telemetry_loopback():
    """The server's own trace client feeds its span pipeline."""
    cfg = config_mod.Config(interval=0.05, percentiles=[0.5],
                            aggregates=["count"], hostname="t")
    msink = simple_sinks.ChannelMetricSink()
    srv = Server(cfg, extra_metric_sinks=[msink])
    srv.start()
    try:
        from veneur_tpu import trace as tm
        tm.report_one(srv.trace_client,
                      ssf_mod.count("veneur.internal", 5))
        deadline = time.time() + 5
        got = []
        while time.time() < deadline and not got:
            srv.flush()
            while not msink.queue.empty():
                got.extend(msink.queue.get())
            time.sleep(0.05)
        assert any(m.name == "veneur.internal" for m in got)
    finally:
        srv.shutdown()


def test_unix_stream_backend_backoff_reconnect(tmp_path):
    """The stream backend retries with additive backoff while the
    listener is away and recovers once it returns
    (trace/backend.go:130-180); the buffered client mode waits for
    buffer space instead of dropping."""
    import socket
    import threading
    import time

    from veneur_tpu import ssf as ssf_mod
    from veneur_tpu import trace as trace_mod

    path = str(tmp_path / "ssf.sock")

    def serve(n_expected, out):
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(1)
        conn, _ = srv.accept()
        buf = b""
        while len(out) < n_expected:
            data = conn.recv(65536)
            if not data:
                break
            buf += data
            import struct
            while len(buf) >= 5:
                _, ln = struct.unpack(">BI", buf[:5])
                if len(buf) < 5 + ln:
                    break
                out.append(ssf_mod.SSFSpan.FromString(buf[5:5 + ln]))
                buf = buf[5 + ln:]
        conn.close()
        srv.close()

    # backend created while the listener does NOT exist yet: connect
    # must retry with backoff and succeed once serve() binds
    got: list = []
    backend = trace_mod.unix_stream_backend(
        path, backoff_s=0.01, max_backoff_s=0.05, connect_timeout_s=5.0)
    t = threading.Thread(target=serve, args=(1, got), daemon=True)

    def delayed_start():
        time.sleep(0.3)
        t.start()

    threading.Thread(target=delayed_start, daemon=True).start()
    span = ssf_mod.SSFSpan(version=0, trace_id=1, id=2, name="op",
                           service="svc", start_timestamp=1,
                           end_timestamp=2)
    backend(span)          # blocks through the backoff loop, then sends
    t.join(timeout=5)
    assert len(got) == 1 and got[0].name == "op"

    # buffered client mode: a full queue WAITS instead of dropping
    slow_release = threading.Event()

    def slow_backend(s):
        slow_release.wait(5.0)

    client = trace_mod.Client(slow_backend, capacity=1,
                              block_timeout_s=2.0)
    client.record(span)    # worker pops this and blocks in the backend
    time.sleep(0.1)
    client.record(span)    # fills the (empty again) 1-slot queue
    t0 = time.time()
    threading.Timer(0.3, slow_release.set).start()
    client.record(span)    # queue genuinely full: must BLOCK for space
    waited = time.time() - t0
    assert waited >= 0.2, waited   # proves the buffered wait happened
    assert client.dropped == 0
    client.close()
