"""Compile-churn hardening (VERDICT r3 #3): every new (keys, depth) pow2
bucket compiles a fresh flush program; prewarm + the persistent cache keep
that out of production flush intervals, the counters make it observable,
and the watchdog knows a compile from a hang."""

import time

import numpy as np

from veneur_tpu.core.aggregator import MetricAggregator
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.samplers.metric_key import MetricKey, MetricScope


def _stage(agg, n_keys: int, samples_per_key: int = 1) -> None:
    rows = np.empty(n_keys, np.int64)
    for i in range(n_keys):
        rows[i] = agg.digests.row_for(
            MetricKey(f"ramp.k{i}", sm.TYPE_HISTOGRAM, ""),
            MetricScope.GLOBAL_ONLY, [])
    all_rows = np.tile(rows, samples_per_key)
    vals = np.random.default_rng(1).gamma(
        2.0, 10.0, n_keys * samples_per_key)
    with agg.lock:
        agg.digests.sample_batch(
            all_rows, vals, np.ones(len(all_rows)))
        agg.digests.touched[rows] = True


def test_cardinality_ramp_compile_events_tracked():
    agg = MetricAggregator(percentiles=[0.5], is_local=False,
                           initial_capacity=4096)
    _stage(agg, 100)
    agg.flush(is_local=False)
    assert agg.compile_events == 1          # first bucket
    assert agg.compile_seconds_total > 0
    _stage(agg, 100)
    agg.flush(is_local=False)
    assert agg.compile_events == 1          # same bucket: cache hit
    _stage(agg, 1000)                       # cardinality ramp
    agg.flush(is_local=False)
    assert agg.compile_events == 2          # new pow2 key bucket
    _stage(agg, 1000, samples_per_key=3)    # deeper staging
    agg.flush(is_local=False)
    assert agg.compile_events == 3          # new depth bucket


def test_prewarm_makes_ramp_compile_free():
    """A ramp across prewarmed buckets must never pay a compile inside
    flush — the soak criterion, scaled to CI."""
    agg = MetricAggregator(percentiles=[0.5], is_local=False,
                           initial_capacity=1024)
    warmed = agg.prewarm([1], max_keys=1024, min_keys=128)
    # 4 key buckets (128..1024) x 5 production programs per bucket:
    # the depth-vector uniform flush and the general weighted flush
    # for the digest family, plus the moments and compactor read-offs
    # (wire payloads route into their arenas on any tier, so every
    # family's programs prewarm too)
    assert warmed == 20
    base = agg.compile_events
    for n in (128, 200, 400, 900, 1024):    # ramp within the buckets
        _stage(agg, n)
        t0 = time.perf_counter()
        res = agg.flush(is_local=False)
        assert len(res.metrics)
        assert agg.compile_events == base   # zero compiles in-flush
    # ... and the guard flag is idle between flushes
    assert not agg.compile_in_progress.is_set()


def test_watchdog_holds_fire_during_compile():
    from tests.test_server import make_config
    from veneur_tpu.core.server import Server

    cfg = make_config(flush_watchdog_missed_flushes=2, interval=0.05)
    srv = Server(cfg)
    fired = []
    srv.shutdown_hook = lambda: fired.append(True)
    srv.last_flush_unix = time.time() - 10      # long overdue...
    srv.aggregator.compile_in_progress.set()    # ...but compiling
    srv.start()
    time.sleep(0.5)
    assert not fired                            # held fire
    srv.aggregator.compile_in_progress.clear()  # compile done, still no
    deadline = time.time() + 2                  # flush: now it kills
    while time.time() < deadline and not fired:
        time.sleep(0.02)
    srv.shutdown()
    assert fired
