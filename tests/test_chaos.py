"""Chaos/backpressure e2e (VERDICT r3 #9, SURVEY §5.3): a global-tier
outage under sustained ingest must degrade with BOUNDED buffering and
per-cause drop accounting (`flusher.go:553-566` classification heritage),
then recover without restarting the local; a slow sink must never stall
the flush loop or starve its sibling sinks."""

import socket
import threading
import time

import numpy as np

from veneur_tpu import config as config_mod
from veneur_tpu import sinks as sink_mod
from veneur_tpu.core.server import Server
from veneur_tpu.sinks import simple as simple_sinks


class _StatsCapture:
    """Real UDP endpoint for the server's self-metric DogStatsD."""

    def __init__(self):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.settimeout(0.05)
        self.addr = f"127.0.0.1:{self.sock.getsockname()[1]}"
        self.lines: list[bytes] = []

    def drain(self) -> bytes:
        while True:
            try:
                data, _ = self.sock.recvfrom(65536)
            except OSError:
                break
            self.lines.extend(data.split(b"\n"))
        return b"\n".join(self.lines)


def test_global_outage_bounded_buffering_and_recovery():
    # the worst outage shape: the global's address ACCEPTS connections
    # but never answers (a wedged host, a half-dead LB target) — every
    # forward hangs to its deadline instead of failing fast
    blackhole = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    blackhole.bind(("127.0.0.1", 0))
    blackhole.listen(16)
    port = blackhole.getsockname()[1]

    stats = _StatsCapture()
    lsink = simple_sinks.ChannelMetricSink()
    local = Server(config_mod.Config(
        statsd_listen_addresses=["udp://127.0.0.1:0"],
        forward_address=f"127.0.0.1:{port}",
        forward_timeout=3.0,                       # slow-failing forwards
        stats_address=stats.addr,
        interval=0.05, percentiles=[0.5], hostname="l"),
        extra_metric_sinks=[lsink])
    local.start()
    try:
        _, addr = local.statsd_addrs[0]
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        rng = np.random.default_rng(5)

        # sustained ingest while the global is down: enough flush ticks
        # that every forward slot is stalled inside its 3s timeout, so
        # later intervals must DROP (bounded buffering), while local
        # emission keeps working untouched
        batches = 0
        for i in range(local.FORWARD_MAX_IN_FLIGHT + 3):
            for v in rng.gamma(2.0, 10.0, 50):
                tx.sendto(b"api.lat:%.2f|h" % v, addr)
            tx.sendto(b"beat:1|c", addr)
            deadline = time.time() + 5
            while time.time() < deadline:
                local._drain_native()
                if local.aggregator.digests.staged_count() >= 50:
                    break
                time.sleep(0.01)
            local.flush()
            batches += 1
        assert local.forward_dropped > 0           # bounded, accounted
        # local pipeline unaffected: every interval's local aggregates
        # and counters came out (egress is async: settle the lanes
        # before reading the channel sink)
        local.egress.settle(timeout_s=10.0)
        got = []
        while not lsink.queue.empty():
            got.extend(lsink.queue.get())
        names = {m.name for m in got}
        assert "api.lat.count" in names and "beat" in names
        blob = stats.drain()
        assert b"forward.error_total" in blob
        assert b"cause:slots_exhausted" in blob

        # recovery: the wedged endpoint dies and a healthy global comes
        # up ON THE SAME PORT; forwarding resumes on the live channel
        # without restarting the local
        blackhole.close()
        g2sink = simple_sinks.ChannelMetricSink()
        g2 = Server(config_mod.Config(grpc_address=f"127.0.0.1:{port}",
                                      interval=0.05, percentiles=[0.5],
                                      hostname="g2"),
                    extra_metric_sinks=[g2sink])
        g2.start()
        try:
            recovered = False
            deadline = time.time() + 30
            while time.time() < deadline and not recovered:
                for v in rng.gamma(2.0, 10.0, 20):
                    tx.sendto(b"api.lat:%.2f|h" % v, addr)
                t0 = time.time() + 2
                while time.time() < t0:
                    local._drain_native()
                    if local.aggregator.digests.staged_count() >= 20:
                        break
                    time.sleep(0.01)
                local.flush()
                g2.flush()
                g2.egress.settle(timeout_s=5.0)
                while not g2sink.queue.empty():
                    for m in g2sink.queue.get():
                        if m.name == "api.lat.50percentile":
                            recovered = True
            assert recovered, "forwarding did not recover after outage"
        finally:
            g2.shutdown()
    finally:
        local.shutdown()


class _SlowSink(sink_mod.BaseMetricSink):
    KIND = "slowtest"

    def __init__(self, block_s: float):
        super().__init__("slow", {})
        self.block_s = block_s
        self.flushes = 0

    def start(self, trace_client=None) -> None:
        pass

    def flush(self, metrics) -> sink_mod.MetricFlushResult:
        self.flushes += 1
        time.sleep(self.block_s)
        return sink_mod.MetricFlushResult(flushed=len(metrics))


def test_slow_sink_straggler_isolation():
    """One sink stuck far past the interval: siblings flush on time every
    interval, the flush loop never blocks past its deadline, and the
    straggler is identified per-sink in self-metrics
    (flush.stragglers_total, the deadline classification heritage)."""
    stats = _StatsCapture()
    fast = simple_sinks.ChannelMetricSink()
    slow = _SlowSink(block_s=3.0)
    srv = Server(config_mod.Config(
        statsd_listen_addresses=["udp://127.0.0.1:0"],
        stats_address=stats.addr,
        interval=0.2, percentiles=[0.5], hostname="s"),
        extra_metric_sinks=[fast, slow])
    srv.start()
    try:
        _, addr = srv.statsd_addrs[0]
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        flush_walls = []
        for i in range(3):
            tx.sendto(b"tick:1|c", addr)
            deadline = time.time() + 5
            base = srv.aggregator.processed
            while time.time() < deadline:
                srv._drain_native()
                if srv.aggregator.processed > base:
                    break
                time.sleep(0.01)
            t0 = time.perf_counter()
            srv.flush()
            flush_walls.append(time.perf_counter() - t0)
        # the fast sink saw every interval, while the slow lane is
        # still grinding through its own queue
        batches = []
        deadline = time.time() + 5
        while time.time() < deadline and len(batches) < 3:
            try:
                batches.append(fast.queue.get(timeout=0.1))
            except Exception:
                pass
        assert len(batches) == 3
        assert all(any(m.name == "tick" for m in b) for b in batches)
        # the flush path never waits on the straggler at all now: the
        # egress handoff is queue-bounded, not deadline-bounded
        assert max(flush_walls) < 3.0
        # and the straggler is identified per sink: interval accounting
        # (which runs on each flush) counts a lane whose current
        # delivery has outlived the interval — no extra ingest needed
        deadline = time.time() + 15
        blob = b""
        while time.time() < deadline:
            blob = stats.drain()
            if b"flush:metric:slow" in blob:
                break
            srv.flush()
            time.sleep(0.2)
        assert b"flush.stragglers_total" in blob
        assert b"flush:metric:slow" in blob
    finally:
        srv.shutdown()


def test_frozen_global_window_dedups_thawed_original():
    """server.sigstop_window fast cell (ISSUE 14): the global's import
    handler freezes past the forward deadline — the in-process twin of
    a SIGSTOP'd peer.  The client must surface DEADLINE_EXCEEDED
    (never hang the flush), the bounded retry re-delivers under the
    SAME chunk identity, and when the window ends the thawed original
    import completes anyway — the dedup ledger must merge exactly
    once.  (The real-signal version is `proc-straggler` in
    testbed/proc_chaos.py.)"""
    from veneur_tpu.testbed.chaos import arm_by_name, run_chaos_arm

    row = run_chaos_arm(arm_by_name("frozen-global-window"), seed=3)
    assert row["ok"], row
    assert row["fired"] >= 1
    assert row["conserved"] and row["dropped_total"] == 0
    assert row["forward_retries"] >= 1
    assert row["duplicates_skipped"] >= 1
