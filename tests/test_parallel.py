"""Multi-device sharded flush tests on the 8-device virtual CPU mesh
(SURVEY.md §4's loopback-gRPC distributed tests re-imagined as
jax.sharding tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veneur_tpu.parallel import flush_step as fs
from veneur_tpu.parallel import mesh as mesh_mod


def test_mesh_shapes():
    mesh = mesh_mod.make_mesh(8)
    assert mesh.shape == {"shard": 4, "replica": 2}
    mesh1 = mesh_mod.make_mesh(1)
    assert mesh1.shape == {"shard": 1, "replica": 1}


def test_sharded_matches_single_device():
    """The pjit'd mesh flush must produce identical results to the
    single-device step on the same inputs."""
    mesh = mesh_mod.make_mesh(8)
    inputs = fs.example_inputs(n_keys=32, n_lanes=4, n_sets=8, seed=3)
    percentiles = jnp.asarray([0.25, 0.5, 0.99], jnp.float32)

    single = fs.flush_step(inputs, percentiles)
    step = fs.make_sharded_flush_step(mesh)
    sharded = step(inputs, percentiles)

    np.testing.assert_allclose(np.asarray(single.digest_eval),
                               np.asarray(sharded.digest_eval),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(single.counter_hi),
                               np.asarray(sharded.counter_hi))
    np.testing.assert_allclose(np.asarray(single.counter_lo),
                               np.asarray(sharded.counter_lo))
    np.testing.assert_allclose(np.asarray(single.set_estimates),
                               np.asarray(sharded.set_estimates))
    assert float(single.unique_ts) == float(sharded.unique_ts)


def test_flush_step_counts_all_points():
    """Every staged point (across all replica depth slices) must land in
    the evaluation: total weight = n_lanes * depth per key."""
    inputs = fs.example_inputs(n_keys=8, n_lanes=3, n_sets=4, depth=32)
    out = fs.flush_step(inputs, jnp.asarray([0.5], jnp.float32))
    # digest_eval columns: [quantiles..., total, sum]
    np.testing.assert_allclose(np.asarray(out.digest_eval)[:, 1],
                               np.full(8, 3 * 32.0), rtol=1e-5)


def test_dryrun_entrypoints():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.digest_eval.shape == (64, 3 + 2)
    g.dryrun_multichip(8)
    g.dryrun_multichip(4)


# ---------------------------------------------------------------------------
# The serving path itself, sharded: the aggregator/server (not synthetic
# example inputs) must produce identical flush output on 1 vs 8 devices.
# ---------------------------------------------------------------------------

def _feed_aggregator(agg):
    from veneur_tpu.samplers import samplers as sm
    from veneur_tpu.samplers.metric_key import MetricScope, UDPMetric

    rng = np.random.default_rng(7)

    def m(name, mtype, value, scope=MetricScope.MIXED, tags=(),
          rate=1.0):
        return UDPMetric(
            name=name, type=mtype, joined_tags=",".join(sorted(tags)),
            value=value, digest=hash(name) & 0xFFFFFFFF,
            sample_rate=rate, scope=scope, tags=list(tags))

    # histograms: several keys, one hot key wide enough to span many
    # ingest waves and lanes
    for v in rng.gamma(2.0, 10.0, 1500):
        agg.process_metric(m("hot.latency", sm.TYPE_HISTOGRAM, float(v)))
    for v in rng.normal(50, 5, 64):
        agg.process_metric(m("warm.timer", sm.TYPE_TIMER, float(v)))
    agg.process_metric(m("gonly.h", sm.TYPE_HISTOGRAM, 3.25,
                         scope=MetricScope.GLOBAL_ONLY))
    agg.process_metric(m("lonly.h", sm.TYPE_HISTOGRAM, 9.5,
                         scope=MetricScope.LOCAL_ONLY))
    # counters / gauges / sets
    for i in range(40):
        agg.process_metric(m("reqs", sm.TYPE_COUNTER, 2.0, rate=0.5))
        agg.process_metric(m("cpu", sm.TYPE_GAUGE, float(i)))
        agg.process_metric(m("users", sm.TYPE_SET, f"user-{i % 17}"))
    # forwarded digests (the global-import path)
    for lane in range(6):
        vals = rng.gamma(3.0, 5.0, 32)
        agg.import_metric(sm.ForwardMetric(
            name="fleet.latency", tags=["az:a"], kind=sm.TYPE_HISTOGRAM,
            scope=MetricScope.MIXED,
            digest_means=sorted(float(v) for v in vals),
            digest_weights=[1.0] * 32,
            digest_min=float(vals.min()), digest_max=float(vals.max()),
            digest_sum=float(vals.sum()),
            digest_rsum=float((1 / vals).sum()),
            digest_compression=100.0))
    agg.import_metric(sm.ForwardMetric(
        name="fleet.users", tags=[], kind=sm.TYPE_SET,
        scope=MetricScope.MIXED,
        hll=_sample_hll()))
    agg.import_metric(sm.ForwardMetric(
        name="fleet.reqs", tags=[], kind=sm.TYPE_COUNTER,
        scope=MetricScope.GLOBAL_ONLY, counter_value=123))


def _sample_hll() -> bytes:
    from veneur_tpu.sketches import hll as hll_mod
    sk = hll_mod.HLLSketch()
    for i in range(500):
        sk.insert(f"member-{i}")
    return sk.marshal()


def _flush_map(agg, is_local):
    res = agg.flush(is_local=is_local, now=1234567)
    metrics = {(m.name, tuple(m.tags), m.type): m.value
               for m in res.metrics}
    fwd = {(f.name, tuple(f.tags), f.kind): f for f in res.forward}
    return metrics, fwd


@pytest.mark.parametrize("is_local", [False, True])
def test_serving_aggregator_1_vs_8_devices(is_local):
    """VERDICT r1 #1: the *serving* aggregator must produce identical
    flush output whether its arenas live on one device or sharded over
    the 8-device (shard, replica) mesh."""
    from veneur_tpu.core.aggregator import MetricAggregator
    from veneur_tpu.samplers import samplers as sm

    kw = dict(percentiles=[0.5, 0.9, 0.99],
              aggregates=sm.parse_aggregates(["min", "max", "count",
                                              "sum", "avg", "hmean"]),
              count_unique_timeseries=True, ingest_lanes=4)
    plain = MetricAggregator(**kw)
    sharded = MetricAggregator(mesh=mesh_mod.make_mesh(8), **kw)

    _feed_aggregator(plain)
    _feed_aggregator(sharded)

    m1, f1 = _flush_map(plain, is_local)
    m2, f2 = _flush_map(sharded, is_local)

    assert set(m1) == set(m2)
    for k in m1:
        np.testing.assert_allclose(m1[k], m2[k], rtol=1e-4, atol=1e-4,
                                   err_msg=str(k))
    assert set(f1) == set(f2)
    for k, fm in f1.items():
        other = f2[k]
        if fm.digest_means:
            np.testing.assert_allclose(fm.digest_means, other.digest_means,
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(fm.digest_weights,
                                       other.digest_weights, rtol=1e-4)
        assert fm.counter_value == other.counter_value
        assert fm.hll == other.hll


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_presharded_overlapped_flush_conserves_intermetrics(n_dev):
    """ISSUE 1 conservation: pre-sharded per-device staging + donated
    input buffers + the dispatch/emit overlap (double-buffering) must
    emit InterMetrics BYTE-identical — exact values, not approximate —
    to the single process-wide device_put funnel, at 1, 2 and 8 virtual
    devices.  Staging placement and donation are pure layout; both arms
    run the identical kernel on identically-built dense matrices, so
    any drift is a launch-path bug."""
    from veneur_tpu.core.aggregator import MetricAggregator

    kw = dict(percentiles=[0.5, 0.9, 0.99], ingest_lanes=4,
              count_unique_timeseries=True)
    funnel = MetricAggregator(mesh=mesh_mod.make_mesh(n_dev),
                              flush_presharded_staging=False, **kw)
    overlap = MetricAggregator(mesh=mesh_mod.make_mesh(n_dev),
                               flush_presharded_staging=True, **kw)

    _feed_aggregator(funnel)
    _feed_aggregator(overlap)

    def key(res):
        return sorted((m.name, tuple(m.tags), m.type, m.value,
                       m.timestamp, m.hostname) for m in res.metrics)

    # funnel arm: the plain blocking flush
    ref1 = funnel.flush(is_local=False, now=1234567)
    # overlapped arm: interval 1 is dispatched but NOT yet emitted while
    # interval 2's ingest is already staging into the arenas (the
    # double-buffer window); emit() then fetches interval 1 — the
    # snapshot must be immune to the concurrent staging
    pend = overlap.flush_dispatch(is_local=False, now=1234567)
    _feed_aggregator(overlap)          # interval 2 ingest mid-kernel
    got1 = pend.emit()
    assert key(got1) == key(ref1)

    # interval 2: row reuse after a donated flush must conserve too
    _feed_aggregator(funnel)
    ref2 = funnel.flush(is_local=False, now=1234568)
    got2 = overlap.flush(is_local=False, now=1234568)
    assert key(got2) == key(ref2)


def test_serving_aggregator_sharded_second_interval():
    """Row reset + reuse across intervals must behave identically when
    sharded (interval-scoped state, worker.go:462-481)."""
    from veneur_tpu.core.aggregator import MetricAggregator

    plain = MetricAggregator(percentiles=[0.5], ingest_lanes=4)
    sharded = MetricAggregator(mesh=mesh_mod.make_mesh(8),
                               percentiles=[0.5], ingest_lanes=4)
    for agg in (plain, sharded):
        _feed_aggregator(agg)
        agg.flush(is_local=False)
        _feed_aggregator(agg)   # same keys again, post-reset
    m1, _ = _flush_map(plain, False)
    m2, _ = _flush_map(sharded, False)
    assert set(m1) == set(m2)
    for k in m1:
        np.testing.assert_allclose(m1[k], m2[k], rtol=1e-4, atol=1e-4,
                                   err_msg=str(k))


def test_production_sets_counters_match_host_math():
    """VERDICT r2 #1: set/counter/unique-ts results produced by the
    *production* aggregator — mesh-sharded SetArena with device pmax, lane-
    striped counter planes with device psum — must equal independently
    computed host math (HLLSketch estimate, exact integer sums)."""
    from veneur_tpu.core.aggregator import MetricAggregator
    from veneur_tpu.samplers import samplers as sm
    from veneur_tpu.samplers.metric_key import MetricScope, UDPMetric
    from veneur_tpu.sketches import hll as hll_mod

    def m(name, mtype, value, rate=1.0, scope=MetricScope.MIXED):
        return UDPMetric(
            name=name, type=mtype, joined_tags="", value=value,
            digest=hash((name, str(value))) & 0xFFFFFFFF,
            sample_rate=rate, scope=scope, tags=[])

    for mesh in (None, mesh_mod.make_mesh(8)):
        agg = MetricAggregator(mesh=mesh, count_unique_timeseries=True,
                               is_local=False)
        # overlapping members across several syncs so the lane pmax is a
        # real union (each sync lands on a different round-robin lane)
        ref = hll_mod.HLLSketch()
        expect_counter = 0
        for wave in range(3):
            for i in range(400):
                member = f"user-{(wave * 250 + i) % 700}"
                agg.process_metric(m("users", sm.TYPE_SET, member))
                ref.insert(member)
            # global-only so it lands on the same row the import merges
            # into (counter imports are coerced to GLOBAL_ONLY)
            agg.process_metric(m("reqs", sm.TYPE_COUNTER, 3.0, rate=0.25,
                                 scope=MetricScope.GLOBAL_ONLY))
            expect_counter += 12
            agg.sync_staged(min_samples=1)   # force a device wave per loop
        # an imported sketch (Set.Merge path) must union in too
        other = hll_mod.HLLSketch()
        for i in range(300):
            other.insert(f"ext-{i}")
            ref.insert(f"ext-{i}")
        agg.import_metric(sm.ForwardMetric(
            name="users", tags=[], kind=sm.TYPE_SET,
            scope=MetricScope.MIXED, hll=other.marshal()))
        agg.import_metric(sm.ForwardMetric(
            name="reqs", tags=[], kind=sm.TYPE_COUNTER,
            scope=MetricScope.GLOBAL_ONLY, counter_value=1_000_000))

        res = agg.flush(is_local=False)
        by = {mm.name: mm.value for mm in res.metrics}
        assert by["users"] == float(ref.estimate()), \
            f"mesh={mesh}: device union diverged from host HLL math"
        assert by["reqs"] == float(expect_counter + 1_000_000)
        assert res.unique_ts is not None and res.unique_ts >= 2


def test_counter_hi_lo_split_exact_beyond_f32():
    """Counter totals ride as (hi, lo) f32 planes; values beyond the f32
    integer range (2^24) must still come back exact (< 2^48)."""
    from veneur_tpu.core.aggregator import MetricAggregator
    from veneur_tpu.samplers import samplers as sm

    big = (1 << 33) + 12345  # not representable in f32
    agg = MetricAggregator()
    agg.import_metric(sm.ForwardMetric(
        name="huge", tags=[], kind=sm.TYPE_COUNTER,
        scope=__import__("veneur_tpu.samplers.metric_key",
                         fromlist=["MetricScope"]).MetricScope.GLOBAL_ONLY,
        counter_value=big))
    res = agg.flush(is_local=False)
    assert {m.name: m.value for m in res.metrics}["huge"] == float(big)


def test_serving_server_1_vs_8_devices():
    """A real global Server configured with mesh_devices=8 must flush the
    same InterMetrics as a single-device server for the same packets."""
    from veneur_tpu import config as config_mod
    from veneur_tpu.core.server import Server
    from veneur_tpu.sinks import simple as simple_sinks

    packets = [b"api.latency:%d|h" % v for v in range(200)]
    packets += [b"reqs:5|c", b"cpu:71|g", b"users:alice|s",
                b"users:bob|s", b"api.latency:9999|h|@0.1"]

    outs = []
    for mesh_devices in (0, 8):
        cfg = config_mod.Config(interval=10.0, percentiles=[0.5, 0.99],
                                hostname="t", mesh_devices=mesh_devices)
        sink = simple_sinks.ChannelMetricSink()
        srv = Server(cfg, extra_metric_sinks=[sink])
        for p in packets:
            srv.handle_metric_packet(p)
        srv.flush()
        batch = sink.queue.get(timeout=5)
        outs.append({(m.name, tuple(m.tags)): m.value for m in batch})
        srv.shutdown()

    assert set(outs[0]) == set(outs[1])
    for k in outs[0]:
        np.testing.assert_allclose(outs[0][k], outs[1][k],
                                   rtol=1e-4, atol=1e-4, err_msg=str(k))


def test_multihost_init_and_meshed_server(tmp_path):
    """Join a (single-process) jax.distributed cluster via the config hook
    and run a meshed server flush over the global device set — the code
    path a real multi-host deployment takes, exercised in a subprocess so
    the cluster state cannot leak into this test process."""
    import os
    import socket as socket_mod
    import subprocess
    import sys

    with socket_mod.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    script = r'''
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from veneur_tpu import config as config_mod
from veneur_tpu.core.server import Server
from veneur_tpu.sinks import simple as simple_sinks

cfg = config_mod.Config(
    interval=10.0, percentiles=[0.5], hostname="mh",
    distributed_coordinator="127.0.0.1:COORD_PORT",
    distributed_num_processes=1, distributed_process_id=0,
    mesh_devices=8, mesh_replicas=2)
sink = simple_sinks.ChannelMetricSink()
srv = Server(cfg, extra_metric_sinks=[sink])
assert jax.process_count() == 1
assert len(jax.devices()) == 8
srv.start()
srv.process_packet_buffer(b"mh.c:5|c\nmh.lat:1|h\nmh.lat:3|h")
srv.flush()
batch = sink.queue.get(timeout=30)
by = {m.name: m.value for m in batch}
assert by["mh.c"] == 5.0
assert by["mh.lat.count"] == 2.0
srv.shutdown()
print("MULTIHOST_OK", dict(srv.mesh.shape))
'''
    script = script.replace("COORD_PORT", str(port))
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=300,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert "MULTIHOST_OK" in out.stdout, (out.stdout, out.stderr[-2000:])


def test_meshed_counter_2p48_boundary():
    """VERDICT r4 item 8: characterize the meshed counter exactness
    boundary.  Totals ride as (hi, lo) f32 planes — exact below 2^48;
    past it the hi plane leaves f32's integer range and the total
    degrades GRACEFULLY (~2^-24 relative error) — no wrap, no
    saturation (the reference's int64 is exact to 2^63, then wraps:
    `samplers/samplers.go:97-150`)."""
    from veneur_tpu.core.aggregator import MetricAggregator
    from veneur_tpu.samplers import samplers as sm
    from veneur_tpu.samplers.metric_key import MetricScope

    def flush_value(count):
        agg = MetricAggregator(mesh=mesh_mod.make_mesh(8))
        agg.import_metric(sm.ForwardMetric(
            name="c", tags=[], kind=sm.TYPE_COUNTER,
            scope=MetricScope.GLOBAL_ONLY, counter_value=count))
        res = agg.flush(is_local=False)
        return {m.name: m.value for m in res.metrics}["c"]

    # exact right up to the boundary: hi = 2^24-1, lo = 2^24-1, both
    # inside f32's integer range
    exact_max = (1 << 48) - 1
    assert flush_value(exact_max) == float(exact_max)

    # just past it: hi = 2^24+1 is the first non-representable f32
    # integer, so the total rounds — bounded relative error, positive,
    # monotonic-ish, NOT wrapped to negative and NOT clamped
    over = (1 << 48) + (1 << 24) + 5
    got = flush_value(over)
    assert got != float(over)                      # boundary is real
    assert got > float(exact_max)                  # no wrap/saturation
    assert abs(got - over) / over < 2.0 ** -23     # graceful degradation


def test_digest_float64_mesh_rejected_at_config_layer():
    """digest_float64 + mesh_devices is rejected when the CONFIG loads
    (not as a deep aggregator error at boot), so -validate-config and
    config dumps catch it (VERDICT r4 item 8)."""
    from veneur_tpu import config as config_mod

    with pytest.raises(ValueError, match="digest_float64"):
        config_mod.load_config_dict(
            {"digest_float64": True, "mesh_devices": 8})
    # each alone stays legal
    config_mod.load_config_dict({"digest_float64": True})
    config_mod.load_config_dict({"mesh_devices": 8})


def test_failed_dispatch_releases_lane_pin():
    """A flush dispatch that raises after the snapshot (device OOM, an
    in-flush compile error) must release the set-lane snapshot pin —
    a leaked pin routes every later lane update through the copying
    kernels for the process lifetime (review finding, round 7)."""
    from veneur_tpu.core.aggregator import MetricAggregator
    from veneur_tpu.samplers import samplers as sm
    from veneur_tpu.samplers.metric_key import MetricKey, MetricScope

    agg = MetricAggregator(mesh=mesh_mod.make_mesh(8),
                           percentiles=[0.5], ingest_lanes=4)
    with agg.lock:
        row = agg.digests.row_for(
            MetricKey("pin.k", sm.TYPE_HISTOGRAM, ""),
            MetricScope.GLOBAL_ONLY, [])
        agg.digests.sample(row, 1.0, 1.0)
        agg.digests.touched[row] = True
    agg.sync_staged(min_samples=1)

    def boom(snap, is_local):
        raise RuntimeError("synthetic dispatch failure")

    agg._dispatch_flush = boom
    with pytest.raises(RuntimeError, match="synthetic"):
        agg.flush_dispatch(is_local=False)
    assert agg.sets._snapshot_inflight == 0

    # and the emit/fetch side: a raising fetch must also unpin
    del agg._dispatch_flush          # restore the real dispatch
    with agg.lock:
        agg.digests.sample(row, 2.0, 1.0)
        agg.digests.touched[row] = True
    agg.sync_staged(min_samples=1)
    pending = agg.flush_dispatch(is_local=False)

    def fetch_boom(snap, pend, seg):
        raise RuntimeError("synthetic fetch failure")

    agg._fetch_flush = fetch_boom
    with pytest.raises(RuntimeError, match="synthetic"):
        pending.emit()
    assert agg.sets._snapshot_inflight == 0
