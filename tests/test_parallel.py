"""Multi-device sharded flush tests on the 8-device virtual CPU mesh
(SURVEY.md §4's loopback-gRPC distributed tests re-imagined as
jax.sharding tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veneur_tpu.parallel import flush_step as fs
from veneur_tpu.parallel import mesh as mesh_mod


def test_mesh_shapes():
    mesh = mesh_mod.make_mesh(8)
    assert mesh.shape == {"shard": 4, "replica": 2}
    mesh1 = mesh_mod.make_mesh(1)
    assert mesh1.shape == {"shard": 1, "replica": 1}


def test_sharded_matches_single_device():
    """The pjit'd mesh flush must produce identical results to the
    single-device step on the same inputs."""
    mesh = mesh_mod.make_mesh(8)
    inputs = fs.example_inputs(n_keys=32, n_lanes=4, n_sets=8, seed=3)
    percentiles = jnp.asarray([0.25, 0.5, 0.99], jnp.float32)

    single = fs.flush_step(inputs, percentiles)
    step = fs.make_sharded_flush_step(mesh)
    sharded = step(inputs, percentiles)

    np.testing.assert_allclose(np.asarray(single.quantiles),
                               np.asarray(sharded.quantiles),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(single.counts),
                               np.asarray(sharded.counts), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(single.counter_totals),
                               np.asarray(sharded.counter_totals))
    np.testing.assert_allclose(np.asarray(single.set_estimates),
                               np.asarray(sharded.set_estimates))
    assert float(single.unique_ts) == float(sharded.unique_ts)


def test_flush_step_merges_lanes():
    """All R lanes' digests must land in the merged state."""
    inputs = fs.example_inputs(n_keys=8, n_lanes=3, n_sets=4)
    out = fs.flush_step(inputs, jnp.asarray([0.5], jnp.float32))
    # state had 32 unit-weight samples per key, each of 3 lanes adds 32
    np.testing.assert_allclose(np.asarray(out.counts),
                               np.full(8, 32.0 * 4), rtol=1e-5)


def test_dryrun_entrypoints():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert out.quantiles.shape == (64, 3)
    g.dryrun_multichip(8)
    g.dryrun_multichip(4)
