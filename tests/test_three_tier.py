"""Full 3-tier chain: local veneur -> proxy -> global veneurs over loopback
gRPC, including a membership change mid-run (ring rebuild) — the e2e shape
of `proxy/handlers/handlers_test.go:65-374` composed with the server fixture
pattern of `server_test.go` (round-1 verdict item #9).

Also covers the proxy's gRPC-TLS listener (proxy.go:190-306) and the
connection open/close stats (grpcstats/stats.go:1-49).
"""

import queue
import socket
import subprocess
import time

import grpc
import pytest

from veneur_tpu import config as config_mod
from veneur_tpu.core.server import Server
from veneur_tpu.forward import convert
from veneur_tpu.forward.client import SEND_METRICS_V2
from veneur_tpu.protocol import metric_pb2
from veneur_tpu.proxy.proxy import Proxy, ProxyConfig
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.samplers.metric_key import MetricScope
from veneur_tpu.sinks import simple as simple_sinks

from tests.test_server import _make_certs  # self-signed CA + certs helper


def boot_global(name):
    cfg = config_mod.Config(
        grpc_address="127.0.0.1:0", interval=0.05,
        percentiles=[0.5], aggregates=["count"], hostname=name)
    sink = simple_sinks.ChannelMetricSink()
    srv = Server(cfg, extra_metric_sinks=[sink])
    srv.start()
    return srv, sink


def collect_names(servers_sinks, want, prefix, timeout=15.0):
    """Flush the globals until `want` distinct prefixed names appear;
    returns {name: global_index}."""
    seen = {}
    deadline = time.time() + timeout
    while time.time() < deadline and len(seen) < want:
        for i, (srv, sink) in enumerate(servers_sinks):
            srv.flush()
            while True:
                try:
                    batch = sink.queue.get_nowait()
                except queue.Empty:
                    break
                for m in batch:
                    if m.name.startswith(prefix):
                        seen.setdefault(m.name, i)
        time.sleep(0.05)
    return seen


def test_three_tier_end_to_end_with_ring_rebuild():
    g1, s1 = boot_global("g1")
    g2, s2 = boot_global("g2")
    g3, s3 = boot_global("g3")
    addr1 = f"127.0.0.1:{g1.grpc_import.port}"
    addr2 = f"127.0.0.1:{g2.grpc_import.port}"
    addr3 = f"127.0.0.1:{g3.grpc_import.port}"

    proxy = Proxy(ProxyConfig(static_destinations=[addr1, addr2],
                              discovery_interval=3600))
    proxy.start()

    lsink = simple_sinks.ChannelMetricSink()
    local = Server(config_mod.Config(
        statsd_listen_addresses=["udp://127.0.0.1:0"],
        forward_address=f"127.0.0.1:{proxy.grpc_port}",
        interval=0.05, percentiles=[0.5], hostname="l"),
        extra_metric_sinks=[lsink])
    local.start()
    try:
        _, uaddr = local.statsd_addrs[0]
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

        # ---- phase 1: two globals in the ring --------------------------
        for i in range(60):
            tx.sendto(b"tt.c%d:1|c|#veneurglobalonly" % i, uaddr)
            tx.sendto(b"tt.h%d:3.5|h\ntt.h%d:9.25|h" % (i, i), uaddr)
        deadline = time.time() + 10
        while time.time() < deadline and local.aggregator.processed < 180:
            time.sleep(0.05)
            local._drain_native()
        assert local.aggregator.processed == 180
        local.flush()

        seen1 = collect_names([(g1, s1), (g2, s2)], 120, "tt.")
        # every forwarded key landed on exactly one global, both used
        counters1 = {n for n in seen1 if n.startswith("tt.c")}
        # mixed-scope digests emit percentiles on the GLOBAL tier; their
        # count/min/max aggregates flush from local scalars on the LOCAL
        # instance (flusher.go:57-74 duality)
        pcts1 = {n for n in seen1 if n.endswith(".50percentile")}
        assert len(counters1) == 60
        assert len(pcts1) == 60
        assert {seen1[n] for n in seen1} == {0, 1}
        local.egress.settle(timeout_s=10.0)   # fan-out is async now
        local_batch = []
        while not lsink.queue.empty():
            local_batch.extend(lsink.queue.get())
        lnames = {m.name: m.value for m in local_batch}
        for i in range(60):
            assert lnames[f"tt.h{i}.count"] == 2.0

        # ---- membership change: g1 leaves, g3 joins --------------------
        proxy.destinations.set_members([addr2, addr3])
        deadline = time.time() + 10
        while time.time() < deadline and proxy.destinations.size() != 2:
            time.sleep(0.05)

        # ---- phase 2: rebuilt ring -------------------------------------
        # `processed` is per-interval (reset by the phase-1 flush) and the
        # flush's own trace span feeds a few self-metrics back in, so wait
        # on the engine's cumulative line total instead
        base_lines = local.native.engine.totals()[0]
        for i in range(60):
            tx.sendto(b"tt2.c%d:1|c|#veneurglobalonly" % i, uaddr)
        deadline = time.time() + 10
        while (time.time() < deadline
               and local.native.engine.totals()[0] < base_lines + 60):
            time.sleep(0.05)
            local._drain_native()
        assert local.native.engine.totals()[0] >= base_lines + 60
        local.flush()
        tx.close()

        seen2 = collect_names([(g2, s2), (g3, s3)], 60, "tt2.")
        assert len(seen2) == 60          # nothing lost across the rebuild
        assert {seen2[n] for n in seen2} == {0, 1}  # g2 AND g3 both serve
        # accounting: any in-flight loss must be visible, not silent
        assert proxy.stats["no_destination"] == 0
        total = proxy.stats["routed"] + proxy.stats["dropped"]
        assert total == proxy.stats["received"]
    finally:
        local.shutdown()
        proxy.stop()
        for g in (g1, g2, g3):
            g.shutdown()


# ---------------------------------------------------------------------------
# gRPC-TLS listener (proxy.go:190-306)
# ---------------------------------------------------------------------------

needs_openssl = pytest.mark.skipif(
    subprocess.run(["which", "openssl"],
                   capture_output=True).returncode != 0,
    reason="openssl unavailable")


def _send_v2(target, creds, metrics, timeout=5.0):
    channel = grpc.secure_channel(target, creds)
    v2 = channel.stream_unary(
        SEND_METRICS_V2,
        request_serializer=metric_pb2.Metric.SerializeToString,
        response_deserializer=lambda b: b)
    try:
        v2(iter(metrics), timeout=timeout)
    finally:
        channel.close()


@needs_openssl
def test_proxy_grpc_tls_requires_client_cert(tmp_path):
    ca, certs = _make_certs(tmp_path)
    skey, scrt = certs["server"]
    ckey, ccrt = certs["client"]
    g, gs = boot_global("gt")
    proxy = Proxy(ProxyConfig(
        static_destinations=[f"127.0.0.1:{g.grpc_import.port}"],
        grpc_tls_address="127.0.0.1:0",
        tls_certificate=scrt, tls_key=skey,
        tls_authority_certificate=ca))
    proxy.start()
    try:
        assert proxy.grpc_tls_port > 0
        fm = sm.ForwardMetric(name="tls.fwd", tags=[], kind="counter",
                              scope=MetricScope.GLOBAL_ONLY,
                              counter_value=7)
        pb = convert.to_pb(fm)
        with open(ca, "rb") as f:
            ca_bytes = f.read()

        # without a client certificate the handshake must fail
        bad = grpc.ssl_channel_credentials(root_certificates=ca_bytes)
        with pytest.raises(grpc.RpcError):
            _send_v2(f"127.0.0.1:{proxy.grpc_tls_port}", bad, [pb],
                     timeout=3.0)

        # with the client certificate the metric flows through to a global
        with open(ckey, "rb") as f:
            key_bytes = f.read()
        with open(ccrt, "rb") as f:
            crt_bytes = f.read()
        good = grpc.ssl_channel_credentials(
            root_certificates=ca_bytes, private_key=key_bytes,
            certificate_chain=crt_bytes)
        _send_v2(f"127.0.0.1:{proxy.grpc_tls_port}", good, [pb])
        deadline = time.time() + 10
        got = {}
        while time.time() < deadline and "tls.fwd" not in got:
            g.flush()
            try:
                for m in gs.queue.get(timeout=0.2):
                    got[m.name] = m.value
            except queue.Empty:
                pass
        assert got["tls.fwd"] == 7.0
    finally:
        proxy.stop()
        g.shutdown()


def test_grpcstats_connection_counters():
    g, _ = boot_global("gc")
    proxy = Proxy(ProxyConfig(
        static_destinations=[f"127.0.0.1:{g.grpc_import.port}"]))
    proxy.start()
    try:
        fm = sm.ForwardMetric(name="st.c", tags=[], kind="counter",
                              scope=MetricScope.GLOBAL_ONLY, counter_value=1)
        channel = grpc.insecure_channel(f"127.0.0.1:{proxy.grpc_port}")
        v2 = channel.stream_unary(
            SEND_METRICS_V2,
            request_serializer=metric_pb2.Metric.SerializeToString,
            response_deserializer=lambda b: b)
        v2(iter([convert.to_pb(fm)]), timeout=5.0)
        v2(iter([convert.to_pb(fm)]), timeout=5.0)
        channel.close()
        snap = proxy.grpc_stats.snapshot()
        # two server-side stream opens+closes; the destination channel
        # reached READY at least once
        assert snap["opened"] >= 2 and snap["closed"] >= 2
        deadline = time.time() + 5
        while (time.time() < deadline
               and proxy.grpc_stats.snapshot()["client_opened"] < 1):
            time.sleep(0.05)
        assert proxy.grpc_stats.snapshot()["client_opened"] >= 1
    finally:
        proxy.stop()
        g.shutdown()
