"""Proxy tier tests, porting `proxy/handlers/handlers_test.go:65-374`,
`proxy/proxy_test.go`, and `proxy/connect/connect_test.go:67-170`: hash
routing stability, fan-in through real gRPC to multiple globals,
destination removal on close, healthcheck states, discovery reconciliation."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from veneur_tpu import config as config_mod
from veneur_tpu.core.server import Server
from veneur_tpu.forward import convert
from veneur_tpu.forward.client import ForwardClient
from veneur_tpu.proxy.consistent import ConsistentHash
from veneur_tpu.proxy.proxy import Proxy, ProxyConfig
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.samplers.metric_key import MetricScope
from veneur_tpu.sinks import simple as simple_sinks


def test_consistent_hash_stability():
    ring = ConsistentHash(["a:1", "b:1", "c:1"])
    keys = [f"metric-{i}" for i in range(1000)]
    before = {k: ring.get(k) for k in keys}
    # removing one member only remaps that member's keys
    ring.remove("c:1")
    moved = sum(1 for k in keys
                if before[k] != ring.get(k) and before[k] != "c:1")
    assert moved == 0
    # re-adding restores the original assignment
    ring.add("c:1")
    after = {k: ring.get(k) for k in keys}
    assert before == after
    # distribution is roughly even
    from collections import Counter
    counts = Counter(before.values())
    assert all(c > 150 for c in counts.values()), counts


def test_empty_ring_raises():
    with pytest.raises(LookupError):
        ConsistentHash().get("x")


def boot_global():
    cfg = config_mod.Config(
        grpc_address="127.0.0.1:0", interval=0.05,
        percentiles=[0.5], aggregates=["count"], hostname="g")
    sink = simple_sinks.ChannelMetricSink()
    srv = Server(cfg, extra_metric_sinks=[sink])
    srv.start()
    return srv, sink


def fm_counter(name, value):
    return sm.ForwardMetric(name=name, tags=[], kind="counter",
                            scope=MetricScope.GLOBAL_ONLY,
                            counter_value=value)


def test_proxy_fan_in_two_globals():
    """1024-host-style fan-in: many metrics through the proxy land
    partitioned across two globals, every key on exactly one."""
    g1, s1 = boot_global()
    g2, s2 = boot_global()
    proxy = Proxy(ProxyConfig(static_destinations=[
        f"127.0.0.1:{g1.grpc_import.port}",
        f"127.0.0.1:{g2.grpc_import.port}"]))
    proxy.start()
    try:
        client = ForwardClient(f"127.0.0.1:{proxy.grpc_port}")
        metrics = [convert.to_pb(fm_counter(f"m{i}", 1)) for i in range(200)]
        client._v2(iter(metrics), timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline and proxy.stats["routed"] < 200:
            time.sleep(0.05)
        assert proxy.stats["routed"] == 200
        # drain destination queues
        time.sleep(0.3)
        g1.flush()
        g2.flush()
        got1, got2 = [], []
        deadline = time.time() + 5
        while time.time() < deadline and len(got1) + len(got2) < 200:
            g1.flush()
            g2.flush()
            while not s1.queue.empty():
                got1.extend(s1.queue.get())
            while not s2.queue.empty():
                got2.extend(s2.queue.get())
            time.sleep(0.05)
        # filter out the servers' own flush-span telemetry (the flush is
        # itself traced and extracted back into metrics)
        names1 = {m.name for m in got1 if m.name.startswith("m")}
        names2 = {m.name for m in got2 if m.name.startswith("m")}
        assert len(names1 | names2) == 200
        assert not (names1 & names2)  # each key on exactly one global
        assert names1 and names2      # both globals participated
        client.close()
    finally:
        proxy.stop()
        g1.shutdown()
        g2.shutdown()


def test_proxy_healthcheck_states():
    proxy = Proxy(ProxyConfig(static_destinations=[]))
    proxy.start()
    try:
        url = f"http://127.0.0.1:{proxy.http_port}/healthcheck"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url)
        assert exc.value.code == 503

        g, _ = boot_global()
        try:
            proxy.discoverer.destinations = [
                f"127.0.0.1:{g.grpc_import.port}"]
            proxy.handle_discovery()
            assert urllib.request.urlopen(url).status == 200
        finally:
            g.shutdown()
    finally:
        proxy.stop()


def test_discovery_reconciliation_and_close_removal():
    g1, _ = boot_global()
    g2, _ = boot_global()
    a1 = f"127.0.0.1:{g1.grpc_import.port}"
    a2 = f"127.0.0.1:{g2.grpc_import.port}"
    proxy = Proxy(ProxyConfig(static_destinations=[a1]))
    proxy.start()
    try:
        assert proxy.destinations.size() == 1
        # membership change: a2 joins, a1 leaves
        proxy.discoverer.destinations = [a2]
        proxy.handle_discovery()
        deadline = time.time() + 5
        while time.time() < deadline and (
                proxy.destinations.size() != 1
                or a2 not in proxy.destinations.stats()):
            time.sleep(0.05)
        assert set(proxy.destinations.stats()) == {a2}

        # killing the destination server removes it on stream close
        g2.shutdown()
        deadline = time.time() + 10
        m = convert.to_pb(fm_counter("x", 1))
        while time.time() < deadline and proxy.destinations.size() > 0:
            proxy.handle_metric(m)  # trigger send -> notice closure
            time.sleep(0.1)
        assert proxy.destinations.size() == 0
    finally:
        proxy.stop()
        g1.shutdown()


def test_ignore_tags_affect_routing_key():
    from veneur_tpu.protocol import metric_pb2
    from veneur_tpu.util.matcher import TagMatcher
    cfg = ProxyConfig(ignore_tags=[TagMatcher(kind="prefix", value="host")])
    proxy = Proxy(cfg)
    try:
        m1 = metric_pb2.Metric(name="a", tags=["host:h1", "env:p"],
                               type=metric_pb2.Counter)
        m2 = metric_pb2.Metric(name="a", tags=["host:h2", "env:p"],
                               type=metric_pb2.Counter)
        assert proxy.routing_key(m1) == proxy.routing_key(m2) == "acounterenv:p"
    finally:
        proxy.stop()


def test_destination_buffer_bound_and_drop_accounting():
    """The send buffer bounds METRICS (not queue items): a wedged
    destination backpressures at ~send_buffer_size and then drops with
    accounting; a graceful close never drops a drained backlog; sent +
    dropped always equals what was accepted (connect.go:231-245
    in-flight-counted-as-dropped contract)."""
    import socket as socket_mod
    from concurrent import futures as cf

    import grpc
    from google.protobuf import empty_pb2

    from veneur_tpu.protocol import forward_pb2, metric_pb2
    from veneur_tpu.proxy.connect import Destination

    gate = threading.Event()
    served = []

    def v1(request, context):
        if len(request.metrics):
            gate.wait(15)           # wedge non-empty batches until told
            served.append(len(request.metrics))
        return empty_pb2.Empty()

    h = grpc.method_handlers_generic_handler("forwardrpc.Forward", {
        "SendMetrics": grpc.unary_unary_rpc_method_handler(
            v1, request_deserializer=forward_pb2.MetricList.FromString,
            response_serializer=empty_pb2.Empty.SerializeToString)})
    server = grpc.server(cf.ThreadPoolExecutor(max_workers=8))
    server.add_generic_rpc_handlers((h,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        d = Destination(f"127.0.0.1:{port}", send_buffer_size=100)
        assert d.batch_mode

        def m(i):
            return metric_pb2.Metric(
                name=f"b{i}", type=metric_pb2.Counter,
                counter=metric_pb2.CounterValue(value=1))

        # fill to just under the cap (senders wedge holding their
        # reservations: the bound covers in-flight batches too)
        for i in range(98):
            d.send(m(i), block_poll_s=0.01)

        def produce_more():
            for i in range(30):
                d.send(m(100 + i), block_poll_s=0.01)

        t = threading.Thread(target=produce_more)
        t.start()
        t.join(timeout=0.7)
        assert t.is_alive()          # backpressured, not accepted
        assert d._buffered <= 100 + 1
        gate.set()                   # unwedge; everything drains
        t.join(timeout=15)
        assert not t.is_alive()
        deadline = time.time() + 10
        while time.time() < deadline and d.sent < 128:
            time.sleep(0.05)
        d.close()
        assert d.sent == 128 and d.dropped == 0
        assert d._buffered == 0
    finally:
        server.stop(0)


def test_destination_oversized_group_not_starved():
    """A routed group larger than the whole buffer cap must still be
    admitted once the buffer has room (review finding: waiting for
    exactly-empty let small sends starve big V1 batches)."""
    from concurrent import futures as cf

    import grpc
    from google.protobuf import empty_pb2

    from veneur_tpu.protocol import forward_pb2, metric_pb2
    from veneur_tpu.proxy.connect import Destination

    def v1(request, context):
        return empty_pb2.Empty()

    h = grpc.method_handlers_generic_handler("forwardrpc.Forward", {
        "SendMetrics": grpc.unary_unary_rpc_method_handler(
            v1, request_deserializer=forward_pb2.MetricList.FromString,
            response_serializer=empty_pb2.Empty.SerializeToString)})
    server = grpc.server(cf.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((h,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        d = Destination(f"127.0.0.1:{port}", send_buffer_size=50)
        big = [metric_pb2.Metric(name=f"o{i}", type=metric_pb2.Counter,
                                 counter=metric_pb2.CounterValue(value=1))
               for i in range(500)]
        assert d.send_many(big, block_poll_s=0.01) == 0
        deadline = time.time() + 10
        while time.time() < deadline and d.sent < 500:
            time.sleep(0.05)
        d.close()
        assert d.sent == 500 and d.dropped == 0
    finally:
        server.stop(0)


def test_proxy_http_introspection_surface():
    """The proxy serves /version, /builddate, /config/{json,yaml}
    (redacted, gated) and /debug/{vars,threads} (gated) alongside the
    healthcheck (cmd/veneur-proxy/main.go:84-102, proxy.go:190-306)."""
    import yaml as yaml_mod

    from veneur_tpu import __version__

    proxy = Proxy(ProxyConfig(static_destinations=[],
                              tls_key="sekrit-path",
                              http_enable_config=True,
                              http_enable_profiling=True))
    proxy.start()
    try:
        base = f"http://127.0.0.1:{proxy.http_port}"
        assert urllib.request.urlopen(
            base + "/version").read().decode() == __version__
        assert urllib.request.urlopen(base + "/builddate").read()

        cfg_json = json.loads(urllib.request.urlopen(
            base + "/config/json").read())
        assert cfg_json["tls_key"] == "REDACTED"
        assert cfg_json["http_enable_config"] is True
        cfg_yaml = yaml_mod.safe_load(urllib.request.urlopen(
            base + "/config/yaml").read())
        assert cfg_yaml["tls_key"] == "REDACTED"
        assert cfg_yaml["forward_service"] == cfg_json["forward_service"]

        dvars = json.loads(urllib.request.urlopen(
            base + "/debug/vars").read())
        assert {"received", "routed", "dropped",
                "destinations", "threads"} <= set(dvars)
        threads = urllib.request.urlopen(
            base + "/debug/threads").read().decode()
        assert "--- thread" in threads
    finally:
        proxy.stop()


def test_proxy_http_gated_endpoints_off_by_default():
    proxy = Proxy(ProxyConfig(static_destinations=[]))
    proxy.start()
    try:
        base = f"http://127.0.0.1:{proxy.http_port}"
        for path in ("/config/json", "/config/yaml",
                     "/debug/vars", "/debug/threads"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + path)
            assert exc.value.code == 404
    finally:
        proxy.stop()


def test_destination_death_reroutes_to_survivor_no_double_landing():
    """ISSUE 5 satellite: destination death via failpoint stream-reset ->
    the victim leaves the ring (breaker open), every key reroutes to a
    surviving global, NO key lands on two globals within a ring epoch,
    /healthcheck stays 200 at one destination (and 503 only at zero),
    and the victim's in-flight loss is accounted, not silent."""
    import queue

    from veneur_tpu import failpoints

    g1, s1 = boot_global()
    g2, s2 = boot_global()
    a1 = f"127.0.0.1:{g1.grpc_import.port}"
    a2 = f"127.0.0.1:{g2.grpc_import.port}"
    proxy = Proxy(ProxyConfig(
        static_destinations=[a1, a2],
        discovery_interval=3600,          # drive discovery manually
        breaker_failure_threshold=1,      # one reset trips
        breaker_reset_timeout=0.3))
    proxy.start()

    def drain(srv, sink, prefix):
        got = set()
        deadline = time.time() + 10
        while time.time() < deadline:
            srv.flush()
            try:
                for m in sink.queue.get(timeout=0.1):
                    if m.name.startswith(prefix):
                        got.add(m.name)
            except queue.Empty:
                break
        while not sink.queue.empty():
            for m in sink.queue.get():
                if m.name.startswith(prefix):
                    got.add(m.name)
        return got

    def send_keys(prefix, n=40):
        for i in range(n):
            proxy.handle_metric(convert.to_pb(
                fm_counter(f"{prefix}{i}", 1)))

    try:
        # phase 1: both globals serve
        send_keys("rr1.")
        deadline = time.time() + 10
        while time.time() < deadline and proxy.stats["routed"] < 40:
            time.sleep(0.05)
        time.sleep(0.3)          # destination queues drain
        seen1a, seen1b = drain(g1, s1, "rr1."), drain(g2, s2, "rr1.")
        assert len(seen1a | seen1b) == 40
        assert not (seen1a & seen1b)
        assert seen1a and seen1b

        # destination death: the next batch RPC on one destination is
        # reset mid-fleet
        failpoints.configure("proxy.send_batch", "stream-reset", times=1)
        try:
            deadline = time.time() + 10
            m = convert.to_pb(fm_counter("rr.sacrifice", 1))
            while time.time() < deadline and proxy.destinations.size() > 1:
                proxy.handle_metric(m)
                time.sleep(0.05)
        finally:
            failpoints.disarm("proxy.send_batch")
        assert proxy.destinations.size() == 1
        survivor = next(iter(proxy.destinations.stats()))
        victim = a1 if survivor == a2 else a2
        bs = proxy.destinations.breaker_stats()
        assert bs[victim]["state"] in ("open", "probe_due")
        # the victim's death dropped at least the reset batch — visible
        # in totals() once the retire thread folds the drained counts in
        deadline = time.time() + 10
        while time.time() < deadline and \
                proxy.destinations.totals()["dropped"] < 1:
            time.sleep(0.05)
        assert proxy.destinations.totals()["dropped"] >= 1

        # healthcheck: 200 with one destination left
        url = f"http://127.0.0.1:{proxy.http_port}/healthcheck"
        assert urllib.request.urlopen(url).status == 200

        # phase 2: rebuilt ring — every key lands on the SURVIVOR only
        sent_before = proxy.destinations.totals()["sent"]
        send_keys("rr2.")
        deadline = time.time() + 10
        while time.time() < deadline and \
                proxy.destinations.totals()["sent"] < sent_before + 40:
            time.sleep(0.05)
        time.sleep(0.3)
        vic_srv, vic_sink = (g1, s1) if victim == a1 else (g2, s2)
        sur_srv, sur_sink = (g2, s2) if victim == a1 else (g1, s1)
        assert len(drain(sur_srv, sur_sink, "rr2.")) == 40
        assert not drain(vic_srv, vic_sink, "rr2.")
        assert proxy.stats["no_destination"] == 0

        # half-open restore: after the cooldown the discovery poll
        # re-dials the (healthy) victim and the ring grows back
        deadline = time.time() + 10
        while time.time() < deadline and proxy.destinations.size() < 2:
            proxy.handle_discovery()
            time.sleep(0.1)
        assert proxy.destinations.size() == 2
        assert proxy.destinations.breaker_stats() == {}
    finally:
        proxy.stop()
        g1.shutdown()
        g2.shutdown()


def test_native_wire_router_matches_python_routing():
    """vn_route must route every metric of a serialized MetricList to
    the same destination the python routing_key + consistent ring pick,
    and its regrouped per-destination buffers must re-parse to the same
    metrics (VERDICT r4 item 5)."""
    import numpy as np

    import veneur_tpu.ingest as ingest_mod
    from veneur_tpu.protocol import forward_pb2, metric_pb2
    from veneur_tpu.proxy.consistent import ConsistentHash

    ingest_mod.load_library()   # loud failure if the engine can't build

    members = ["a:1", "b:1", "c:1"]
    ring = ConsistentHash(members)
    rng = np.random.default_rng(3)
    metrics = []
    for i in range(500):
        t = int(rng.integers(0, 5))
        m = metric_pb2.Metric(
            name=f"svc.metric.{i % 97}", type=t,
            tags=[f"env:prod", f"shard:{i % 7}"][: int(rng.integers(0, 3))])
        if t == 0:
            m.counter.value = i
        elif t == 1:
            m.gauge.value = float(i)
        metrics.append(m)
    payload = forward_pb2.MetricList(metrics=metrics).SerializeToString()

    hashes = np.asarray([h for h, _ in ring._ring], np.uint32)
    didx = np.asarray([members.index(m) for _, m in ring._ring], np.int32)
    routed = ingest_mod.route_metric_list(payload, hashes, didx,
                                          len(members), chunk_max=64)
    assert routed is not None

    type_names = {0: "counter", 1: "gauge", 2: "histogram", 3: "set",
                  4: "timer"}
    want: dict[int, list] = {i: [] for i in range(len(members))}
    for m in metrics:
        key = f"{m.name}{type_names[m.type]}{','.join(m.tags)}"
        want[members.index(ring.get(key))].append(m)

    total = 0
    for d, (chunks, chunk_counts, count) in enumerate(routed):
        got = []
        for ch, cn in zip(chunks, chunk_counts):
            parsed = forward_pb2.MetricList.FromString(ch).metrics
            assert len(parsed) == cn <= 64
            got.extend(parsed)
        assert len(got) == count == len(want[d]), d
        for g, w in zip(got, want[d]):
            assert g.SerializeToString() == w.SerializeToString()
        total += count
    assert total == len(metrics)
