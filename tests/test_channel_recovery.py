"""gRPC wedged-subchannel audit (ROADMAP #5e): every long-lived
channel must either re-dial fresh after a peer death or run
wait_for_ready, so a killed-and-revived peer is always re-reachable.

  ForwardClient   live sends stay fail-fast (an UNAVAILABLE failure is
                  provably undelivered and therefore spool-able — a
                  wait-for-ready DEADLINE would be ambiguous), and
                  exhausted transport failures re-dial a FRESH channel
                  (the proxy-destination immunity pattern).  Spool
                  replay already runs wait_for_ready (PR 14).
  Destinations    immune by construction (pinned here): a failed
                  Destination is destroyed with its channel, and the
                  post-revival re-add dials a fresh one.
  Falconer sink   re-dials after consecutive send failures.
"""

import socket
import time

import pytest

from veneur_tpu.forward.client import ForwardClient, RetryPolicy
from veneur_tpu.samplers import samplers as sm
from veneur_tpu.sources.proxy import GrpcImportServer


def _free_port() -> int:
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    return port


def _mk_metrics(n: int) -> list:
    return [sm.ForwardMetric(name=f"cr.c{i}", tags=[],
                             kind=sm.TYPE_COUNTER, scope=2,
                             counter_value=1) for i in range(n)]


def test_forward_client_redials_fresh_channel_after_peer_death():
    """Kill-and-revive regression: a ForwardClient whose sends
    exhausted against a dead peer must re-dial a fresh channel, so the
    revived peer (same port) is reached by the NEXT send without
    inheriting the dead subchannel's backoff state."""
    port = _free_port()
    imported = []
    srv = GrpcImportServer(f"127.0.0.1:{port}",
                           import_metric=imported.append)
    srv.start()
    client = ForwardClient(f"127.0.0.1:{port}", timeout_s=2.0,
                           retry=RetryPolicy(attempts=2,
                                             backoff_base_s=0.01))
    try:
        client.send(_mk_metrics(3), epoch=1)
        assert client.stats()["sent"] == 3
        # peer dies hard (no drain)
        srv.server.stop(grace=0)
        with pytest.raises(Exception):
            client.send(_mk_metrics(3), epoch=2)
        st = client.stats()
        assert st["dropped"] == 3
        # the exhausted transport failure re-dialed a fresh channel
        assert st["redials"] == 1
        # peer revives on the SAME port
        srv2 = GrpcImportServer(f"127.0.0.1:{port}",
                                import_metric=imported.append)
        srv2.start()
        try:
            deadline = time.time() + 10.0
            delivered = False
            epoch = 3
            while time.time() < deadline and not delivered:
                try:
                    client.send(_mk_metrics(3), epoch=epoch)
                    delivered = True
                except Exception:
                    epoch += 1
                    time.sleep(0.1)
            assert delivered, "revived peer never re-reached"
            assert len(imported) == 6
        finally:
            srv2.stop()
    finally:
        client.close()


def test_forward_client_failpoint_failures_never_redial():
    """Injected chaos faults must not churn channels: only REAL
    transport failures trigger the fresh re-dial."""
    from veneur_tpu import failpoints
    port = _free_port()
    srv = GrpcImportServer(f"127.0.0.1:{port}",
                           import_metric=lambda m: None)
    srv.start()
    client = ForwardClient(f"127.0.0.1:{port}", timeout_s=2.0,
                           retry=RetryPolicy(attempts=2,
                                             backoff_base_s=0.01))
    failpoints.configure("forward.send", "grpc-error",
                         code="UNAVAILABLE")
    try:
        with pytest.raises(Exception):
            client.send(_mk_metrics(2), epoch=1)
        st = client.stats()
        assert st["dropped"] == 2
        assert st["redials"] == 0
    finally:
        failpoints.clear()
        client.close()
        srv.stop()


def test_redial_rate_limited_and_stubs_swap():
    """Back-to-back exhaustions re-dial at most once per
    REDIAL_MIN_INTERVAL_S, and the channel object actually changes."""
    port = _free_port()   # nothing ever listens here
    client = ForwardClient(f"127.0.0.1:{port}", timeout_s=0.5,
                           retry=RetryPolicy(attempts=1,
                                             backoff_base_s=0.01))
    try:
        ch0 = client.channel
        with pytest.raises(Exception):
            client.send(_mk_metrics(1), epoch=1)
        assert client.stats()["redials"] == 1
        assert client.channel is not ch0
        ch1 = client.channel
        with pytest.raises(Exception):
            client.send(_mk_metrics(1), epoch=2)
        # within the rate limit: no second re-dial
        assert client.stats()["redials"] == 1
        assert client.channel is ch1
    finally:
        client.close()


def test_proxy_destination_revival_dials_fresh_channel():
    """Pin the proxy tier's immunity: a destination whose peer died is
    destroyed with its channel, and the post-revival re-add (what the
    discovery poll / breaker probe does) constructs a NEW Destination
    on a NEW channel — no subchannel state survives the death."""
    from veneur_tpu.proxy.destinations import Destinations
    port = _free_port()
    imported = []
    srv = GrpcImportServer(f"127.0.0.1:{port}",
                           import_metric=imported.append)
    srv.start()
    addr = f"127.0.0.1:{port}"
    dests = Destinations(send_buffer_size=64, send_timeout_s=2.0,
                         dial_timeout_s=2.0, breaker_threshold=1,
                         breaker_reset_s=0.05)
    try:
        dests.add([addr])
        d0 = dests.get("anykey")
        ch0 = d0.channel
        from veneur_tpu.protocol import metric_pb2
        m = metric_pb2.Metric(name="cr.x", type=metric_pb2.Counter)
        m.counter.value = 1
        assert d0.send_many([m]) == 0
        deadline = time.time() + 5.0
        while not imported and time.time() < deadline:
            time.sleep(0.02)
        assert imported
        srv.server.stop(grace=0)
        # drive sends until the broken RPC destroys the destination
        deadline = time.time() + 10.0
        while dests.size() and time.time() < deadline:
            try:
                dests.get("anykey").send_many([m])
            except LookupError:
                break
            time.sleep(0.05)
        assert dests.size() == 0, "dead destination not torn down"
        # revive on the same port; wait out the breaker cooldown, then
        # the re-add IS the half-open probe — on a fresh channel
        srv2 = GrpcImportServer(addr, import_metric=imported.append)
        srv2.start()
        try:
            deadline = time.time() + 10.0
            while not dests.size() and time.time() < deadline:
                dests.add([addr])
                time.sleep(0.05)
            assert dests.size() == 1
            d1 = dests.get("anykey")
            assert d1 is not d0 and d1.channel is not ch0
            before = len(imported)
            assert d1.send_many([m]) == 0
            deadline = time.time() + 5.0
            while len(imported) == before and time.time() < deadline:
                time.sleep(0.02)
            assert len(imported) > before
        finally:
            srv2.stop()
    finally:
        dests.clear()


def test_falconer_sink_redials_after_consecutive_errors():
    from veneur_tpu import sinks as sink_mod
    from veneur_tpu.sinks.falconer import FalconerSpanSink
    from veneur_tpu.ssf import SSFSpan
    port = _free_port()   # dead target
    sink = FalconerSpanSink(sink_mod.SinkSpec(
        kind="falconer",
        config={"target": f"127.0.0.1:{port}",
                "send_timeout": 0.2, "redial_after": 2}))
    sink.start()
    ch0 = sink._channel
    span = SSFSpan()
    sink.ingest(span)
    assert sink.errors == 1 and sink.redials == 0
    sink.ingest(span)
    assert sink.errors == 2 and sink.redials == 1
    assert sink._channel is not ch0
