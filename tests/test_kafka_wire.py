"""Kafka wire-protocol producer tests: a fake broker speaking Metadata v1
+ Produce v3 parses the produced RecordBatch v2 back (CRC32C verified),
covering leader routing, murmur2 partitioning, reconnect-and-refresh, and
the kafka sink's native path end-to-end."""

import socket
import struct
import threading

import pytest

from veneur_tpu import sinks as sink_mod
from veneur_tpu.util import kafka_wire as kw


# ---------------------------------------------------------------------------
# known-vector checks (independent of our own encoder/decoder pairing)
# ---------------------------------------------------------------------------

def test_crc32c_known_vectors():
    # RFC 3720 / published Castagnoli vectors
    assert kw.crc32c(b"") == 0
    assert kw.crc32c(b"123456789") == 0xE3069283
    assert kw.crc32c(b"\x00" * 32) == 0x8A9136AA


def test_murmur2_known_vectors():
    # org.apache.kafka.common.utils.Utils.murmur2 vectors (as published
    # signed 32-bit by the Java/kafka-python partitioner tests)
    cases = {
        b"21": -973932308,
        b"foobar": -790332482,
        b"a-little-bit-long-string": -985981536,
        b"a-little-bit-longer-string": -1486304829,
        b"lkjh234lh9fiuh90y23oiuhsafujhadof229phr9h19h89h8": -58897971,
    }
    for data, signed in cases.items():
        assert kw.murmur2(data) == signed & 0xFFFFFFFF


def test_varint_roundtrip():
    for n in (0, 1, -1, 5, -5, 127, 128, -128, 300, -300, 2 ** 31):
        buf = kw._varint(n)
        got, off = kw.read_varint(buf, 0)
        assert got == n and off == len(buf)


def test_record_batch_roundtrip():
    msgs = [(b"k1", b"v1"), (None, b"keyless"), (b"", b"empty-key"),
            (b"k2", b"x" * 500)]
    batch = kw.encode_record_batch(msgs, base_ts_ms=1_700_000_000_000)
    assert kw.parse_record_batch(batch) == msgs


def test_record_batch_crc_detects_corruption():
    batch = bytearray(kw.encode_record_batch([(b"k", b"v")]))
    batch[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        kw.parse_record_batch(bytes(batch))


# ---------------------------------------------------------------------------
# fake broker
# ---------------------------------------------------------------------------

class FakeBroker:
    """Just enough broker: Metadata v1 advertising itself as leader of
    `n_partitions`, Produce v3 storing parsed records per partition."""

    def __init__(self, n_partitions=4, fail_first_produces=0):
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.n_partitions = n_partitions
        self.records: dict[int, list] = {}
        self.produce_requests = 0
        self.fail_first_produces = fail_first_produces
        self._stop = False
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._client, args=(conn,),
                             daemon=True).start()

    def _client(self, conn):
        try:
            while True:
                head = self._read(conn, 4)
                if head is None:
                    return
                (length,) = struct.unpack(">i", head)
                req = self._read(conn, length)
                api, ver, corr = struct.unpack_from(">hhi", req, 0)
                off = 8
                (cid_len,) = struct.unpack_from(">h", req, off)
                off += 2 + max(cid_len, 0)
                body = req[off:]
                if api == kw.API_METADATA:
                    resp = self._metadata(body)
                elif api == kw.API_PRODUCE:
                    resp = self._produce(body)
                else:
                    return
                payload = struct.pack(">i", corr) + resp
                conn.sendall(struct.pack(">i", len(payload)) + payload)
        except OSError:
            pass
        finally:
            conn.close()

    def _read(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _metadata(self, body):
        (n,) = struct.unpack_from(">i", body, 0)
        (tlen,) = struct.unpack_from(">h", body, 4)
        topic = body[6:6 + tlen].decode()
        host = b"127.0.0.1"
        out = struct.pack(">i", 1)                       # 1 broker
        out += struct.pack(">i", 0)                      # node id
        out += struct.pack(">h", len(host)) + host
        out += struct.pack(">i", self.port)
        out += struct.pack(">h", -1)                     # rack null
        out += struct.pack(">i", 0)                      # controller
        out += struct.pack(">i", 1)                      # 1 topic
        out += struct.pack(">h", 0)                      # err
        out += struct.pack(">h", len(topic)) + topic.encode()
        out += b"\x00"                                   # is_internal
        out += struct.pack(">i", self.n_partitions)
        for pid in range(self.n_partitions):
            out += struct.pack(">hii", 0, pid, 0)        # err, pid, leader
            out += struct.pack(">ii", 1, 0)              # replicas [0]
            out += struct.pack(">ii", 1, 0)              # isr [0]
        return out

    def _produce(self, body):
        self.produce_requests += 1
        fail = self.produce_requests <= self.fail_first_produces
        off = 0
        (tid_len,) = struct.unpack_from(">h", body, off)
        off += 2 + max(tid_len, 0)
        acks, timeout = struct.unpack_from(">hi", body, off)
        off += 6
        (n_topics,) = struct.unpack_from(">i", body, off)
        off += 4
        parts_out = b""
        n_parts_total = 0
        for _ in range(n_topics):
            (tlen,) = struct.unpack_from(">h", body, off)
            off += 2
            topic = body[off:off + tlen].decode()
            off += tlen
            (n_parts,) = struct.unpack_from(">i", body, off)
            off += 4
            for _ in range(n_parts):
                (pid,) = struct.unpack_from(">i", body, off)
                off += 4
                (blen,) = struct.unpack_from(">i", body, off)
                off += 4
                batch = body[off:off + blen]
                off += blen
                err = 3 if fail else 0   # UNKNOWN_TOPIC_OR_PARTITION
                if not fail:
                    self.records.setdefault(pid, []).extend(
                        kw.parse_record_batch(batch))
                parts_out += struct.pack(">ihqq", pid, err, 0, -1)
                n_parts_total += 1
            topic_b = topic.encode()
            head = (struct.pack(">h", len(topic_b)) + topic_b
                    + struct.pack(">i", n_parts_total))
        return (struct.pack(">i", 1) + head + parts_out
                + struct.pack(">i", 0))  # throttle

    def stop(self):
        self._stop = True
        self.sock.close()


# ---------------------------------------------------------------------------
# producer against the fake broker
# ---------------------------------------------------------------------------

def test_produce_partitions_and_delivers():
    broker = FakeBroker(n_partitions=4)
    try:
        p = kw.KafkaProducer([f"127.0.0.1:{broker.port}"])
        msgs = [(b"key-%d" % i, b"value-%d" % i) for i in range(100)]
        acked = p.produce_batch("metrics", msgs)
        assert acked == 100
        got = [m for pid in broker.records for m in broker.records[pid]]
        assert sorted(got) == sorted(msgs)
        # murmur2 placement matches the Java default partitioner
        for pid, recs in broker.records.items():
            for key, _ in recs:
                assert kw.partition_for(key, 4) == pid
        assert len(broker.records) > 1  # actually spread
        p.close()
    finally:
        broker.stop()


def test_produce_retries_after_error():
    broker = FakeBroker(n_partitions=2, fail_first_produces=1)
    try:
        p = kw.KafkaProducer([f"127.0.0.1:{broker.port}"])
        acked = p.produce_batch("t", [(b"k", b"v")])
        assert acked == 1   # first produce errors, retry succeeds
        # errors count only messages lost AFTER the retry, not transient
        # failures that recovered
        assert p.errors == 0
        p.close()
    finally:
        broker.stop()


def test_kafka_sink_native_path_end_to_end():
    from veneur_tpu.sinks.kafka import KafkaMetricSink, KafkaSpanSink
    from veneur_tpu.protocol import ssf_pb2

    broker = FakeBroker(n_partitions=3)
    try:
        sink = KafkaMetricSink(sink_mod.SinkSpec(kind="kafka", config={
            "kafka_brokers": f"127.0.0.1:{broker.port}",
            "metric_topic": "veneur-metrics",
            "metric_serializer": "json"}))
        sink.start(None)
        from veneur_tpu.samplers.samplers import InterMetric
        res = sink.flush([
            InterMetric(name=f"m{i}", timestamp=1, value=float(i),
                        tags=["a:b"], type="counter") for i in range(20)])
        assert res.flushed == 20 and res.dropped == 0
        values = [v for pid in broker.records
                  for _, v in broker.records[pid]]
        assert len(values) == 20
        assert all(b'"Name"' in v for v in values)
        broker.records.clear()

        span_sink = KafkaSpanSink(sink_mod.SinkSpec(kind="kafka", config={
            "kafka_brokers": f"127.0.0.1:{broker.port}",
            "span_topic": "veneur-spans"}))
        span_sink.start(None)
        for i in range(5):
            span_sink.ingest(ssf_pb2.SSFSpan(
                version=0, trace_id=100 + i, id=i + 1, name="op",
                service="svc", start_timestamp=1, end_timestamp=2))
        span_sink.flush()
        spans = [m for pid in broker.records for m in broker.records[pid]]
        assert len(spans) == 5 and span_sink.dropped == 0
    finally:
        broker.stop()


def test_partial_failure_does_not_duplicate():
    """A failed partition retries ONLY its own messages — successes on
    other partitions are not re-sent (no duplicate writes)."""
    broker = FakeBroker(n_partitions=2)
    # fail partition 1 on the first produce request only
    orig = broker._produce
    state = {"first": True}

    def flaky_produce(body):
        resp = orig(body)
        if state["first"]:
            state["first"] = False
            # rewrite partition 1's error code to NOT_LEADER (6) and
            # un-store its records
            import struct as st
            out = bytearray(resp)
            # response layout: n_topics, topic, n_parts, then
            # (pid i32, err i16, base i64, ts i64)*
            off = 4
            (tlen,) = st.unpack_from(">h", out, off)
            off += 2 + tlen
            (n_parts,) = st.unpack_from(">i", out, off)
            off += 4
            for _ in range(n_parts):
                (pid,) = st.unpack_from(">i", out, off)
                if pid == 1:
                    st.pack_into(">h", out, off + 4, 6)
                    broker.records.pop(1, None)
                off += 22
            return bytes(out)
        return resp

    broker._produce = flaky_produce
    try:
        p = kw.KafkaProducer([f"127.0.0.1:{broker.port}"])
        msgs = [(b"key-%d" % i, b"v%d" % i) for i in range(40)]
        by_part = {}
        for k, v in msgs:
            by_part.setdefault(kw.partition_for(k, 2), []).append((k, v))
        acked = p.produce_batch("t", msgs)
        assert acked == 40
        # partition 0's messages delivered exactly once
        assert sorted(broker.records[0]) == sorted(by_part[0])
        assert sorted(broker.records[1]) == sorted(by_part[1])
        p.close()
    finally:
        broker.stop()


def test_bad_broker_address_rejected_early():
    with pytest.raises(ValueError, match="host:port"):
        kw.KafkaProducer(["broker-without-port"])


def test_unreachable_broker_counts_errors_not_raises():
    p = kw.KafkaProducer(["127.0.0.1:1"])  # nothing listens on port 1
    acked = p.produce_batch("t", [(b"k", b"v")])
    assert acked == 0 and p.errors == 1
    p.close()


def test_oversized_batch_splits_into_chunks():
    """A flush larger than max_batch_bytes splits into multiple produce
    rounds instead of one broker-rejected RecordBatch."""
    broker = FakeBroker(n_partitions=1)
    try:
        p = kw.KafkaProducer([f"127.0.0.1:{broker.port}"],
                             max_batch_bytes=10_000)
        msgs = [(b"k%d" % i, b"v" * 500) for i in range(100)]  # ~57KB
        acked = p.produce_batch("t", msgs)
        assert acked == 100
        assert broker.produce_requests >= 6  # genuinely chunked
        got = broker.records[0]
        assert sorted(got) == sorted(msgs)   # exactly once, all delivered
        p.close()
    finally:
        broker.stop()


def test_empty_key_hashes_like_java():
    # empty key is hashed (sticky), not round-robined
    pid = kw.partition_for(b"", 4)
    assert all(kw.partition_for(b"", 4, counter=c) == pid
               for c in range(8))
    assert pid == (kw.murmur2(b"") & 0x7FFFFFFF) % 4
